"""ctt-hbm: device-resident pipelines — the warm HBM buffer cache.

The host side is latency-tolerant (three-stage pipeline, async prefetch,
decoded-chunk LRU) but HBM was cold per job: every serve job re-uploaded
its device arrays even when the previous job on the same daemon had just
uploaded the identical bytes.  This module is the device analog of the
decoded-chunk LRU (``utils/store.py``), one layer up:

  * :class:`DeviceBufferCache` — a process-wide LRU of *device* arrays
    keyed by ``(volume, bounding box, sharding, transform tag)`` with an
    HBM byte budget (``CTT_HBM_CACHE_MB``).  Eviction calls ``.delete()``
    on the evicted jax arrays explicitly — HBM must actually free, GC
    latency is not a memory plan.
  * Freshness rides the SAME per-chunk store signatures the chunk LRU
    already computes (POSIX ``(inode, mtime_ns, size)``, remote
    ``(ETag, Last-Modified, Content-Length)``): a :class:`BatchSource`
    carries the signature tuple of every chunk overlapping the batch's
    halo'd bounding box, and any rewrite — in-process, cross-process, or
    out-of-band on the object store — turns the next probe into a miss.
    Stale data is structurally impossible; stale HBM merely re-uploads.
  * ``fetch_or_upload`` — the one call sites use: probe, else build the
    :class:`DeviceBatch` (the task's ``put_sharded`` uploads) under a
    process-wide two-slot transfer gate and insert it.

The transfer gate (:func:`upload_slot`) is also the serve-concurrency
dispatch-interleaving policy: at ``concurrency > 1`` two jobs' upload
bursts interleave through the same two slots instead of convoying one
job's entire transfer queue ahead of the other's compute.

Budget resolution: the ``CTT_HBM_CACHE_MB`` environment (default 0 — a
plain cold workflow process keeps exactly the pre-hbm behavior), or the
owning :class:`~cluster_tools_tpu.runtime.workflow.ExecutionContext`'s
``hbm_cache_mb`` argument — the serve daemon passes its ``hbm_cache_mb``
config (default 512), which is where cross-job reuse lives.  ``0``
disables everything: no probes, no stats, no cache entries.

Eviction guard (ctt-hier follow-up to the original hazard note): an
evicted array's ``.delete()`` could race a concurrent job still holding
the value between a ``get`` and the dispatch consuming it (serve
``concurrency > 1``) — the loss degraded to the per-block fallback, a
silent slowdown.  The executors now wrap every device-consuming stage in
:class:`use_guard`, and eviction defers the ``.delete()`` of any batch
evicted while a guard is active until the LAST guard exits
(``device.deferred_deletes``): HBM frees a dispatch later at worst, and
an in-flight dispatch can never lose its buffers.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Tuple

import numpy as np

from ..obs import metrics as obs_metrics

__all__ = [
    "DeviceBufferCache", "DeviceBatch", "BatchSource", "cache",
    "cache_budget_bytes", "set_cache_budget", "dataset_source",
    "fetch_or_upload", "sharded_device_batch", "batch_device",
    "require_data", "upload_slot", "use_guard", "stack_block_batches",
    "split_stacked", "hbm_stack",
]


# ---------------------------------------------------------------------------
# eviction guard: defer evicted .delete() past in-flight dispatches
#
# The window between a cache get and the dispatch consuming the arrays is
# unlocked by design (the dispatch itself can run seconds).  Instead of
# per-entry pin counts at every call site, the executors mark the whole
# device-consuming scope with ``use_guard``; any eviction inside ANY
# active guard queues its .delete() and the last guard to exit drains the
# queue.  Epoch semantics: deletes are delayed by at most one overlapping
# dispatch, never lost.

_GUARD_LOCK = threading.Lock()
_ACTIVE_GUARDS = 0
_DEFERRED_DELETES: list = []


class use_guard:
    """Scope during which evicted device batches must not be freed yet
    (a dispatch may still be consuming them)."""

    def __enter__(self):
        global _ACTIVE_GUARDS
        with _GUARD_LOCK:
            _ACTIVE_GUARDS += 1
        return self

    def __exit__(self, *exc):
        global _ACTIVE_GUARDS
        drain: list = []
        with _GUARD_LOCK:
            _ACTIVE_GUARDS -= 1
            if _ACTIVE_GUARDS == 0 and _DEFERRED_DELETES:
                drain = list(_DEFERRED_DELETES)
                _DEFERRED_DELETES.clear()
        for batch in drain:
            batch.delete()
        return False


def _delete_or_defer(batch: "DeviceBatch") -> None:
    """Free an evicted batch now, or queue it while any dispatch guard is
    active (the eviction/in-flight-dispatch race fix)."""
    with _GUARD_LOCK:
        if _ACTIVE_GUARDS > 0:
            _DEFERRED_DELETES.append(batch)
            obs_metrics.inc("device.deferred_deletes")
            return
    batch.delete()


def cache_budget_bytes() -> int:
    """``CTT_HBM_CACHE_MB`` (default 0 = disabled); malformed values
    degrade to the default like every other CTT_* switch."""
    raw = os.environ.get("CTT_HBM_CACHE_MB")
    try:
        mb = float(raw) if raw is not None else 0.0
    except (TypeError, ValueError):
        mb = 0.0
    return max(int(mb * 1024 * 1024), 0)


@dataclass
class DeviceBatch:
    """One batch's device-resident upload: the task-defined tuple of
    device arrays (stacked data + aux planes), the real (unpadded) batch
    size, and the host bytes that crossed (or would cross) to HBM."""

    arrays: Tuple[Any, ...]
    n: int
    nbytes: int

    def delete(self) -> None:
        for arr in self.arrays:
            fn = getattr(arr, "delete", None)
            if fn is not None:
                try:
                    fn()
                except Exception:  # ctt: noqa[CTT009] double-delete of an already-freed buffer must not mask the eviction path
                    pass


@dataclass(frozen=True)
class BatchSource:
    """Identity + freshness of the store region one device upload covers.

    ``key`` is the hashable cache key (dataset path/key, block ids, halo,
    transform tag, sharding descriptor); ``sig`` is the per-chunk store
    signature tuple the probe validates against — the chunk LRU's own
    freshness keys, one level up."""

    key: Tuple
    sig: Tuple = field(hash=False, compare=False, default=())


class DeviceBufferCache:
    """Process-wide LRU of :class:`DeviceBatch` entries in HBM.

    Same shape as the decoded-chunk LRU: entries carry their source
    signature, a mismatched probe is a miss (and evicts the stale entry),
    and inserts evict least-recently-used entries past the byte budget —
    but eviction here calls ``.delete()`` so the HBM is returned to the
    allocator immediately."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, Tuple[Tuple, DeviceBatch]]" = (
            OrderedDict()
        )
        self._bytes = 0

    @property
    def nbytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, source: BatchSource) -> Optional[DeviceBatch]:
        evicted = None
        with self._lock:
            entry = self._entries.get(source.key)
            if entry is None:
                return None
            if entry[0] != source.sig:
                # store rewrite since the upload: drop the stale buffers
                evicted = self._pop_locked(source.key)
            else:
                self._entries.move_to_end(source.key)
                return entry[1]
        if evicted is not None:
            obs_metrics.inc("device.cache_evictions")
            _delete_or_defer(evicted)
            self._publish()
        return None

    def put(self, source: BatchSource, batch: DeviceBatch) -> None:
        if self.max_bytes <= 0 or batch.nbytes > self.max_bytes:
            return
        evicted = []
        with self._lock:
            old = self._pop_locked(source.key)
            if old is not None:
                evicted.append(old)
            self._entries[source.key] = (source.sig, batch)
            self._bytes += batch.nbytes
            while self._bytes > self.max_bytes and self._entries:
                key = next(iter(self._entries))
                evicted.append(self._pop_locked(key))
        for batch_out in evicted:
            obs_metrics.inc("device.cache_evictions")
            _delete_or_defer(batch_out)
        self._publish()

    def _pop_locked(self, key) -> Optional[DeviceBatch]:
        entry = self._entries.pop(key, None)
        if entry is None:
            return None
        self._bytes -= entry[1].nbytes
        return entry[1]

    def clear(self) -> None:
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
            self._bytes = 0
        for _, batch in entries:
            _delete_or_defer(batch)
        self._publish()

    def _publish(self) -> None:
        obs_metrics.set_gauge("device.cache_bytes", self._bytes)

    def describe(self) -> dict:
        with self._lock:
            return {
                "budget_bytes": self.max_bytes,
                "bytes": self._bytes,
                "entries": len(self._entries),
            }


def cache() -> Optional[DeviceBufferCache]:
    """The process context's device-buffer cache, or None when disabled
    (budget 0) — callers treat None as 'every probe misses, skip the
    stats'.  When no context exists yet AND the env budget is 0 this
    returns None without creating (or activating) one, so plain library
    reads stay exactly as cheap as before ctt-hbm."""
    from .workflow import ExecutionContext

    ctx = ExecutionContext._PROCESS
    if ctx is None:
        if cache_budget_bytes() <= 0:
            return None
        ctx = ExecutionContext.process_context()
    dc = ctx.device_cache()
    return dc if dc is not None and dc.max_bytes > 0 else None


def set_cache_budget(max_bytes: Optional[int]) -> int:
    """Override the process cache budget (tests / tools); returns the
    previous budget.  ``None`` restores the ``CTT_HBM_CACHE_MB``
    resolution; any change clears (and deletes) cached entries."""
    from .workflow import ExecutionContext

    ctx = ExecutionContext.process_context()
    dc = ctx.device_cache()
    prev = dc.max_bytes
    dc.max_bytes = (
        cache_budget_bytes() if max_bytes is None else max(int(max_bytes), 0)
    )
    dc.clear()
    return prev


# ---------------------------------------------------------------------------
# source construction (identity + freshness)


def _put_devices(b: int, config) -> list:
    """The device list ``put_sharded`` would pick for a [b, ...] batch
    (empty = plain single-device transfer)."""
    if config is not None and config.get("target", "tpu") != "tpu":
        return []
    try:
        from ..parallel.mesh import resolve_devices

        devices = resolve_devices(config)
    except Exception:
        return []
    if b < len(devices):
        devices = devices[:b]
    return list(devices) if len(devices) > 1 else []


def _shard_desc(b: int, config) -> Tuple:
    """The device placement ``put_sharded`` would choose for a [b, ...]
    batch — part of the cache key so a hit can only serve an array with
    the exact sharding the consumer's dispatch expects."""
    devices = _put_devices(b, config)
    if not devices:
        return ("single",)
    return tuple(str(d) for d in devices)


def dataset_source(ds, path: str, key: str, blocking, block_ids, halo,
                   tag: Tuple, config) -> Optional[BatchSource]:
    """Build the :class:`BatchSource` of one batch read: identity from
    ``(path, key, block ids, halo, tag, sharding)``, freshness from the
    per-chunk signatures of every chunk overlapping the batch's halo'd
    bounding box (``Dataset.region_signature`` — the chunk LRU's keys).
    Returns None when the device cache is disabled, the dataset cannot
    sign regions (hdf5), or a signature probe failed transiently — the
    caller then runs the plain uncached path."""
    if cache() is None or not block_ids:
        return None
    sig_fn = getattr(ds, "region_signature", None)
    if sig_fn is None:
        return None
    halo = tuple(int(h) for h in (halo or (0,) * blocking.ndim))
    from ..parallel.dispatch import batch_outer_boxes

    _, lo, hi, _ = batch_outer_boxes(blocking, block_ids, halo)
    extra = len(ds.shape) - blocking.ndim
    lead = tuple(slice(0, s) for s in ds.shape[:extra])
    bb = lead + tuple(slice(b, e) for b, e in zip(lo, hi))
    sig = sig_fn(bb)
    if sig is None:
        return None
    return BatchSource(
        key=(path, key, tuple(int(b) for b in block_ids), halo, tuple(tag),
             _shard_desc(len(block_ids), config)),
        sig=sig,
    )


# ---------------------------------------------------------------------------
# upload path

# the double-buffer transfer gate: at most two uploads in flight process-
# wide.  Per dispatch this bounds the upload lookahead to two batches
# (batch k computes while k+1 transfers and k+2 waits at the gate); at
# serve concurrency > 1 it is the interleaving policy — two jobs' upload
# bursts alternate through the shared slots instead of convoying.
UPLOAD_SLOTS = 2
_UPLOAD_GATE = threading.BoundedSemaphore(UPLOAD_SLOTS)
_INFLIGHT_LOCK = threading.Lock()
_INFLIGHT = 0


class upload_slot:
    """Context manager accounting one in-flight host→HBM transfer."""

    def __enter__(self):
        global _INFLIGHT
        _UPLOAD_GATE.acquire()
        with _INFLIGHT_LOCK:
            _INFLIGHT += 1
            obs_metrics.set_gauge("device.inflight_uploads", _INFLIGHT)
        return self

    def __exit__(self, *exc):
        global _INFLIGHT
        with _INFLIGHT_LOCK:
            _INFLIGHT -= 1
            obs_metrics.set_gauge("device.inflight_uploads", _INFLIGHT)
        _UPLOAD_GATE.release()
        return False


def fetch_or_upload(source: Optional[BatchSource],
                    build: Callable[[], DeviceBatch]) -> DeviceBatch:
    """The one upload call: probe the cache under ``source`` (None =
    uncacheable), else ``build()`` the device batch under an upload slot
    and insert it.  Counters: ``device.uploads_skipped`` on a hit,
    ``device.upload_bytes`` for bytes that actually crossed."""
    dc = cache() if source is not None else None
    if dc is not None:
        hit = dc.get(source)
        if hit is not None:
            obs_metrics.inc("device.uploads_skipped")
            return hit
    with upload_slot():
        batch = build()
    obs_metrics.inc("device.upload_bytes", int(batch.nbytes))
    if dc is not None:
        dc.put(source, batch)
    return batch


def sharded_device_batch(data: np.ndarray, config) -> DeviceBatch:
    """``put_sharded`` as a :class:`DeviceBatch` builder — the standard
    single-array upload of a stacked block batch."""
    from ..parallel.mesh import put_sharded

    xb, n = put_sharded(data, config)
    return DeviceBatch(arrays=(xb,), n=n, nbytes=int(data.nbytes))


def batch_device(batch, config,
                 build: Optional[Callable[[], DeviceBatch]] = None
                 ) -> DeviceBatch:
    """Device arrays for a :class:`~..parallel.dispatch.BlockBatch`:
    the probe result stamped at read time (``batch.device``), else a
    cache fetch under ``batch.source`` (the transform tag is baked into
    the source key at read time), else ``build()`` (default: the plain
    ``put_sharded`` of ``batch.data``).  Raises when the batch was a
    probe-hit stub (``data is None``) whose entry was evicted in the
    meantime — the executor's per-block fallback re-reads it."""
    dev = getattr(batch, "device", None)
    if dev is not None:
        return dev
    if build is None:
        def build() -> DeviceBatch:
            return sharded_device_batch(require_data(batch), config)
    source = getattr(batch, "source", None)
    if source is not None and not isinstance(source, BatchSource):
        source = None
    batch.device = fetch_or_upload(source, build)
    return batch.device


def require_data(batch) -> np.ndarray:
    """The batch's host data, or a loud error when the batch is a device
    probe stub whose cache entry has since been evicted (the per-block
    fallback then re-reads from the store)."""
    if batch.data is None:
        raise RuntimeError(
            "device-cache entry evicted between read probe and compute; "
            "per-block fallback re-reads the batch"
        )
    return batch.data


def cached_put_from_store(ds, mesh, *, source_path: str, source_key: str,
                          tag: Tuple, dtype=None, pad_to=None,
                          transform=None, pad_value=0):
    """``parallel.mesh.put_from_store`` through the device-buffer cache:
    the whole-volume upload of a collective task (sharded watershed /
    problem) keyed by ``(path, key, full volume, tag, mesh)`` and
    signature-validated against every chunk of the dataset — the
    "uploaded ONCE, stays resident" pattern of ShardedWsProblemTask,
    generalized so back-to-back serve jobs on the same volume skip the
    re-upload entirely.  ``tag`` must pin every transform-relevant config
    knob (invert, normalization mode, output dtype)."""
    from ..parallel.mesh import put_from_store

    def build() -> DeviceBatch:
        arr = put_from_store(
            ds, mesh, dtype=dtype, pad_to=pad_to, transform=transform,
            pad_value=pad_value,
        )
        out_dtype = np.dtype(dtype) if dtype is not None else ds.dtype
        nbytes = int(np.prod(arr.shape)) * out_dtype.itemsize
        return DeviceBatch(arrays=(arr,), n=int(arr.shape[0]), nbytes=nbytes)

    source = None
    if cache() is not None:
        sig_fn = getattr(ds, "region_signature", None)
        sig = sig_fn(tuple(slice(0, s) for s in ds.shape)) if sig_fn else None
        if sig is not None:
            mesh_desc = tuple(str(d) for d in np.ravel(mesh.devices))
            source = BatchSource(
                key=(source_path, source_key, "fullvol", tuple(tag),
                     str(np.dtype(dtype)) if dtype is not None else None,
                     int(pad_to or 0), mesh_desc),
                sig=sig,
            )
    return fetch_or_upload(source, build).arrays[0]


# ---------------------------------------------------------------------------
# aggregated dispatch helpers (lever b): stack k read payloads' BlockBatches
# into one (sum_B, ...) stack so the executor issues ONE device dispatch per
# batch stack — the coarse-CC (n_tiles, ...) shape generalized.  Pure host
# reshuffling; the kernels are vmapped over the leading axis, so the stacked
# dispatch is byte-identical to the per-batch (and per-block) results.


def stack_block_batches(batches, config=None):
    """Concatenate BlockBatches along the batch axis (geometry included).
    When every member is a device probe hit the stack concatenates ON
    device (no host round trip) and re-places the result exactly as
    ``put_sharded`` would have placed the stacked host read, so stacked
    cache hits and stacked uploads dispatch identically.  A stack mixing
    probe hits and host reads has neither full host data nor full device
    state — ``batch_device`` then raises and the executor's per-block
    fallback re-reads (a rare cache-boundary case, never wrong bytes)."""
    from ..parallel.dispatch import BlockBatch

    if len(batches) == 1:
        return batches[0]
    datas = [b.data for b in batches]
    data = (
        np.concatenate(datas, axis=0)
        if all(d is not None for d in datas) else None
    )
    valids = [b.valid for b in batches]
    valid = (
        np.concatenate(valids, axis=0)
        if all(v is not None for v in valids) else None
    )
    out = BlockBatch(
        data=data, valid=valid,
        blocks=[bh for b in batches for bh in b.blocks],
        block_ids=[bid for b in batches for bid in b.block_ids],
    )
    sources = [getattr(b, "source", None) for b in batches]
    if all(s is not None for s in sources):
        # the stacked upload is its own cache line: key = member keys
        # chained, sig = member sigs chained (any member rewrite misses)
        out.source = BatchSource(
            key=("stack",) + tuple(s.key for s in sources),
            sig=tuple(s.sig for s in sources),
        )
    devices = [getattr(b, "device", None) for b in batches]
    if data is None and all(d is not None for d in devices):
        out.device = _concat_device(devices, config)
    return out


def _concat_device(devices, config) -> DeviceBatch:
    """Stack per-chunk DeviceBatches that were all probe hits: device-side
    concatenate of each array slot (sliced to the real n first), then
    re-pad and re-place to the exact ``put_sharded`` layout of the
    equivalent stacked host upload."""
    import jax.numpy as jnp

    n = sum(d.n for d in devices)
    devs = _put_devices(n, config)
    pad = (-n) % len(devs) if devs else 0
    arrays = []
    for slot in range(len(devices[0].arrays)):
        parts = [d.arrays[slot][: d.n] for d in devices]
        arr = jnp.concatenate(parts, axis=0)
        if pad:
            arr = jnp.concatenate([arr, jnp.repeat(arr[-1:], pad, axis=0)])
        if devs:
            from ..parallel.mesh import get_mesh, shard_batch

            arr = shard_batch(arr, get_mesh(devs))
        arrays.append(arr)
    return DeviceBatch(arrays=tuple(arrays), n=n,
                       nbytes=sum(d.nbytes for d in devices))


def split_block_batch(batch, counts) -> list:
    """Slice a stacked BlockBatch back into per-chunk BlockBatches (the
    geometry inverse of :func:`stack_block_batches`) — device/source
    state is deliberately dropped: the splits exist only for the write
    stage, which consumes geometry + results."""
    from ..parallel.dispatch import BlockBatch

    out, off = [], 0
    for c in counts:
        out.append(BlockBatch(
            data=None if batch.data is None else batch.data[off: off + c],
            valid=None if batch.valid is None else batch.valid[off: off + c],
            blocks=batch.blocks[off: off + c],
            block_ids=batch.block_ids[off: off + c],
        ))
        off += c
    return out


def split_stacked(results: np.ndarray, counts) -> list:
    """Split a stacked per-block result array back into per-chunk arrays
    (the inverse of the leading-axis concatenation)."""
    out, off = [], 0
    for c in counts:
        out.append(results[off: off + c])
        off += c
    return out


def hbm_stack(config) -> int:
    """Batches per fused device dispatch: the ``hbm_stack`` config knob,
    else the measured pin (``CTT_HBM_STACK`` env, else the backend-tagged
    ``tools/chip_modes.json`` entry written by tools/chip_session.py when
    aggregation measured ≥ 1.1× — the CTT_DEVICE_BATCH idiom), else 1
    (off — the pre-hbm dispatch shape); malformed values degrade to 1."""
    raw = config.get("hbm_stack")
    if raw is None:
        from ..ops import _backend

        raw = _backend.pinned_value("CTT_HBM_STACK")
    try:
        n = int(raw) if raw is not None else 1
    except (TypeError, ValueError):
        n = 1
    return max(n, 1)
