"""Worker entry point for batch-scheduler executors.

One scheduler job = one invocation of this module: it loads the pickled
task, runs blocks through the in-process local path, and writes a
machine-readable per-job status JSON (the positive-success analog of the
reference's ``processed job/block`` log lines, function_utils.py:11-16 —
parsed back by the submitting process without log-grepping).

Two assignment modes (ctt-steal), selected by the job config file:

  * ``"queue_dir"`` present — the worker PULLS leased block batches from
    the shared work queue (``runtime/queue.py``) until every item has a
    terminal record: it claims unclaimed items, requeues expired leases
    of dead peers, and duplicates stragglers first-writer-wins.  The
    status file then reports the blocks this worker actually ran (plus
    the item ids), and the submitting process aggregates from the
    queue's ownership records.  Any process pointed at the job dir can
    join late and just start pulling — elasticity is the default.
  * ``"block_ids"`` present — the frozen static share (the reference's
    round-robin split), byte-identical to the pre-steal path.

Live telemetry (ctt-watch): when tracing is enabled the worker heartbeats
(``obs/heartbeat.py`` — role ``worker`` + its scheduler job id) so the
driver-side ``obs watch`` sees its progress and flags it stale if it hangs
or dies; a scheduler SIGTERM (the common preemption path) flushes metrics
+ trace shards + one final ``exiting`` heartbeat before the process dies
(``install_sigterm_flush``), so preempted work is visible, not lost.

Failure surfaces (ctt-fault):

  * a corrupt ``task.pkl`` / ``job_N.json`` (torn write, version skew,
    truncated ship) no longer dies with only a traceback on stderr — the
    setup phase writes a failed status JSON with ``"setup_failed": true``
    and the traceback under ``errors["setup"]``, so the submitter
    aggregates a real diagnostic instead of inferring "job died before
    writing status";
  * fault sites ``worker.job`` (before the status write — ``kill``
    simulates a job dying statusless, the case the submitter's
    no-status-file branch covers) and ``worker.exit`` (after the status
    write) make both crash windows testable;
  * the status write is durable (tmp + fsync + atomic replace via the
    store helper).

    python -m cluster_tools_tpu.runtime.cluster_worker <job_dir> <job_id>
"""

from __future__ import annotations

import json
import os
import pickle
import sys
import traceback


def job_paths(job_dir: str, job_id: int):
    return (
        os.path.join(job_dir, "task.pkl"),
        os.path.join(job_dir, f"job_{job_id}.json"),
        os.path.join(job_dir, f"job_{job_id}.status.json"),
    )


def _write_status(status_path: str, status: dict) -> None:
    from ..utils.store import atomic_write_bytes

    atomic_write_bytes(status_path, json.dumps(status).encode())


def _drain_queue(queue_dir, task, blocking, config, executor, job_id):
    """ctt-steal pull loop: claim leased block batches until the queue is
    fully resolved, running each through the local executor.  The
    heartbeat total grows with each pull (there is no frozen share), so
    ``obs watch`` shows a per-worker progress that reflects the blocks
    this process actually owns."""
    from ..obs import heartbeat as obs_heartbeat
    from .queue import WorkQueue, drain

    queue = WorkQueue(queue_dir)
    ident = getattr(task, "identifier", "unknown")
    pulled = [0]

    def run_item(claim):
        pulled[0] += len(claim.block_ids)
        obs_heartbeat.note_task(ident, pulled[0], grid=blocking.grid_shape)
        return executor.run_blocks(task, blocking, claim.block_ids, config)

    stats = drain(queue, run_item, job_id=job_id)
    return {
        "done": [int(b) for b in stats["done"]],
        "failed": [int(b) for b in stats["failed"]],
        "errors": {str(k): v for k, v in stats["errors"].items()},
        "items": [int(k) for k in stats["items"]],
        "duplicated_items": [int(k) for k in stats["duplicated"]],
        "sched": "steal",
    }


def run_job(job_dir: str, job_id: int) -> int:
    task_path, config_path, status_path = job_paths(job_dir, job_id)
    # preemption hook first: a SIGTERM during setup must already flush
    # whatever telemetry exists (no-ops when tracing is disabled)
    from ..obs import heartbeat as obs_heartbeat

    obs_heartbeat.install_sigterm_flush()
    obs_heartbeat.ensure_started(role="worker", job_id=job_id)
    try:
        with open(task_path, "rb") as f:
            task = pickle.load(f)
        with open(config_path) as f:
            job = json.load(f)
    except Exception:
        # machine-readable setup failure: the submitter keeps this job's
        # blocks failed (done is empty) AND gets the traceback, instead of
        # a bare "job wrote no status file"
        _write_status(status_path, {
            "done": [],
            "failed": [],
            "errors": {"setup": traceback.format_exc()},
            "setup_failed": True,
        })
        return 1

    # ctt-obs: a scheduler job inherits CTT_TRACE_DIR/CTT_RUN_ID from the
    # submitting process's environment (worker_env), so its spans land in
    # the same run as the driver's — bootstrap happened at obs import
    from .. import faults
    from ..obs import trace as obs_trace
    from ..utils.blocking import Blocking
    from .executor import LocalExecutor

    blocking = Blocking(job["shape"], job["block_shape"])
    config = dict(job["config"])
    ident = getattr(task, "identifier", "unknown")
    queue_dir = job.get("queue_dir")
    static_ids = job.get("block_ids") or []
    # this job's share in the heartbeat stream: run_blocks is driven
    # directly here (no Task.run), so the task/total fields need setting.
    # Queue mode has no frozen share — the total grows per pulled item.
    obs_heartbeat.note_task(ident, len(static_ids), grid=blocking.grid_shape)
    # inside one scheduler job, blocks run through the plain local path.
    # The local executor reads ``max_jobs`` as its thread-pool width, but
    # in here that key means the SCHEDULER JOB COUNT — a worker that
    # spawned one block thread per sibling job was a config misuse
    # (n_jobs x n_jobs block concurrency across the cluster).  Intra-job
    # width is ``threads_per_job``, the reference's per-job knob.
    config["target"] = "local"
    try:
        config["max_jobs"] = max(int(config.get("threads_per_job", 1)), 1)
    except (TypeError, ValueError):
        config["max_jobs"] = 1
    executor = LocalExecutor(config)
    try:
        with obs_trace.span(
            f"job_{job_id}", kind="host", task=ident,
            blocks=len(static_ids),
        ):
            if queue_dir:
                status = _drain_queue(
                    queue_dir, task, blocking, config, executor, job_id,
                )
            else:
                done, failed, errors = executor.run_blocks(
                    task, blocking, static_ids, config
                )
                status = {
                    "done": [int(b) for b in done],
                    "failed": [int(b) for b in failed],
                    "errors": {str(k): v for k, v in errors.items()},
                }
    except Exception:
        status = {
            "done": [],
            "failed": [int(b) for b in static_ids],
            "errors": {"job": traceback.format_exc()},
        }
    # chaos seam: `kill` here dies WITHOUT a status file (the submitter's
    # no-status branch + task retry must recover the job's blocks)
    faults.check("worker.job", id=job_id)
    _write_status(status_path, status)
    # ... and here dies AFTER the status landed (crash on the way out —
    # recorded work must survive, the submitter sees a normal status)
    faults.check("worker.exit", id=job_id)
    # final exiting heartbeat: obs watch distinguishes this clean exit
    # from a kill (whose last heartbeat goes stale instead)
    obs_heartbeat.stop(final=True)
    obs_trace.flush()  # short-lived process: don't rely on atexit ordering
    return 0 if not status["failed"] else 1


if __name__ == "__main__":
    sys.exit(run_job(sys.argv[1], int(sys.argv[2])))
