"""Two-level JSON configuration: one global config + one config per task.

Keeps the reference's config ergonomics (SURVEY.md §5 "Config / flag system";
reference cluster_tasks.py:180-248): ``global.config`` carries volume decomposition
and scheduling knobs, ``<task_name>.config`` carries per-task behavior, and task
*parameters* (paths/keys) stay constructor arguments — config files carry behavior,
parameters carry wiring.

TPU-specific knobs replace the reference's Slurm fields: ``target`` selects the
execution backend (``tpu`` = batched jit dispatch over a device mesh, ``local`` =
host loop, the parity oracle), ``device_batch_size`` controls how many blocks ride
one device dispatch.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

# reference default production block shape: cluster_tasks.py:225
DEFAULT_GLOBAL_CONFIG: Dict[str, Any] = {
    "block_shape": [50, 512, 512],
    "roi_begin": None,
    "roi_end": None,
    "block_list_path": None,
    "target": "local",
    "max_jobs": 1,
    "max_num_retries": 0,
    "retry_failure_fraction": 0.5,
    # None resolves, in order: CTT_DEVICE_BATCH env, the measured pin in
    # tools/chip_modes.json (backend-tagged), then the backend default —
    # 1 block/dispatch on XLA-CPU (vmapped while_loops run max-over-batch
    # rounds — measured ~2x slower than sequential singles on one core),
    # 8 on accelerators (amortizes dispatch latency)
    "device_batch_size": None,
    # batches in flight on the tpu target: depth d overlaps batch i+1's host
    # chunk IO with batch i's device execution (1 = serial loop)
    "pipeline_depth": 2,
    # ctt-stream: workflows may declare fused task chains (one streaming
    # pass, elided intermediates); False forces task-at-a-time execution
    # everywhere (CTT_STREAM_FUSION=0 is the per-process override)
    "stream_fusion": True,
    # ctt-hbm aggregated dispatch: read payloads per fused device dispatch
    # in the staged pipeline (the coarse-CC (n_tiles, ...) stacked shape
    # generalized to the split-protocol kernels).  None resolves
    # CTT_HBM_STACK, else 1 — the pre-hbm one-dispatch-per-batch shape;
    # host IO granularity (read/write batches) is unchanged either way.
    "hbm_stack": None,
    # ctt-steal: cluster-job block assignment — None = auto ("steal" on
    # multi-job runs of retryable tasks, "static" otherwise); "static"
    # restores the reference's frozen round-robin split byte-identically.
    # CTT_SCHED is the per-process override.  Workers pull batches of
    # steal_batch_size blocks (None = ~4 pulls per worker) under leases
    # renewed every steal_lease_s seconds (None = the heartbeat cadence);
    # steal_duplicate enables straggler re-dispatch (first-writer-wins).
    "sched": None,
    "steal_batch_size": None,
    "steal_lease_s": None,
    "steal_duplicate": True,
    "devices": None,  # None = all jax.devices()
    "seed": 0,
    # multi-host scale-out: run the SAME driver script on every host with
    # process_id 0..num_processes-1 (or set CTT_PROCESS_ID / CTT_NUM_PROCESSES
    # in each host's environment).  Blocks shard round-robin over processes,
    # the chunked store on the shared filesystem is the data plane, and
    # single-shot merge tasks run on process 0 while peers wait on its status
    # file — the DCN-free control plane the reference uses (SURVEY.md §2.9)
    "num_processes": 1,
    "process_id": 0,
    "peer_wait_timeout_s": 3600.0,
}


def process_topology(gconf: Dict[str, Any]):
    """(process_id, num_processes) from the global config, overridable via the
    CTT_PROCESS_ID / CTT_NUM_PROCESSES environment (one driver per host)."""
    num = int(os.environ.get("CTT_NUM_PROCESSES", gconf.get("num_processes", 1) or 1))
    pid = int(os.environ.get("CTT_PROCESS_ID", gconf.get("process_id", 0) or 0))
    if not 0 <= pid < max(num, 1):
        raise ValueError(f"process_id {pid} out of range for {num} processes")
    return pid, max(num, 1)

# ctt-serve: the persistent serving daemon's knobs.  Lives here (not in
# serve/) because it follows the same two-level JSON convention: the
# daemon reads ``serve.config`` from its state dir over these defaults,
# exactly like tasks read ``<task>.config`` over DEFAULT_TASK_CONFIG.
DEFAULT_SERVE_CONFIG: Dict[str, Any] = {
    "host": "127.0.0.1",   # loopback only: the daemon is a local submission
    "port": 0,             # endpoint (0 = ephemeral, recorded in serve.json)
    # executor threads running builds concurrently.  1 keeps device
    # dispatch strictly serialized (the deterministic default); raising it
    # interleaves independent jobs' host stages on one warm process.
    "concurrency": 1,
    # admission control: total unfinished jobs (queued + running) the
    # daemon accepts before rejecting submissions with 429
    "max_queue_depth": 64,
    # per-tenant in-flight ceiling (None disables); "tenant_quotas" maps
    # tenant name -> override for heavier/lighter tenants
    "tenant_quota": 8,
    "tenant_quotas": {},
    # job-lease renewal cadence (None = the heartbeat cadence): a daemon
    # killed mid-job leaves a lease that goes stale after 3x this and is
    # requeued by the next daemon on the same state dir
    "lease_s": None,
    # SIGTERM drain: how long to wait for in-flight jobs before dying
    # anyway (queued jobs are durable either way)
    "drain_timeout_s": 300.0,
    # ctt-fleet: retry budget per job — a job may burn this many lease
    # generations (daemon deaths / crashes mid-job) before the next
    # would-be claimant quarantines it as a failed result instead of
    # re-executing (<= 0 restores unbounded retries)
    "max_job_gens": 3,
    # fleet identity (None = <host>-<pid>-<n>); stamps leases and names
    # the daemon.<id>.json fleet heartbeat in the state dir
    "daemon_id": None,
    # ctt-microbatch: cross-tenant job aggregation.  After claiming a
    # job, the executor holds it open for up to microbatch_window_s,
    # coalescing queued jobs with the same microbatch_signature (same
    # workflow/type/configs) into ONE stacked dispatch of at most
    # microbatch_max_jobs members — claimed in (-priority, seq) order at
    # window close, so a higher-priority arrival during the window beats
    # lower-priority queue residents.  p99 latency of an aggregated job
    # is bounded by the window; 0 disables (exact per-job dispatch).
    "microbatch_window_s": 0.02,
    "microbatch_max_jobs": 8,
    # ctt-hbm warm device-buffer cache budget (MB) for the daemon's
    # ExecutionContext: back-to-back jobs on the same volume reuse the
    # HBM-resident uploads instead of re-transferring.  0 disables (the
    # plain cold-process default); plain processes opt in via
    # CTT_HBM_CACHE_MB instead.
    "hbm_cache_mb": 512.0,
}


def serve_config(state_dir: Optional[str]) -> Dict[str, Any]:
    """Daemon config: ``serve.config`` in the state dir over the defaults
    (same merge discipline as :func:`global_config`)."""
    conf = dict(DEFAULT_SERVE_CONFIG)
    conf.update(read_config(state_dir, "serve"))
    return conf


DEFAULT_TASK_CONFIG: Dict[str, Any] = {
    "threads_per_job": 1,
    # host threads for a block batch's chunk reads (gzip-decode bound;
    # set 1 for backends where concurrency buys nothing, e.g. hdf5)
    "read_threads": 4,
    "time_limit": 60,
    "mem_limit": 2,
}


def _config_path(config_dir: str, name: str) -> str:
    from ..utils.store_backend import backend_for

    backend = backend_for(config_dir)
    return backend.join(config_dir, f"{name}.config")


def write_config(config_dir: str, name: str, conf: Dict[str, Any]) -> str:
    from ..utils.store_backend import backend_for

    backend = backend_for(config_dir)
    backend.makedirs(config_dir)
    path = _config_path(config_dir, name)
    # config dirs are shared state (serve daemons rewrite configs between
    # jobs, workers re-read them) — a reader must never see a torn file;
    # backend writes are atomic on POSIX and single-object PUTs remotely
    backend.write_bytes(
        path, json.dumps(conf, indent=2, sort_keys=True).encode()
    )
    return path

def write_global_config(config_dir: str, conf: Optional[Dict[str, Any]] = None) -> str:
    merged = dict(DEFAULT_GLOBAL_CONFIG)
    if conf:
        merged.update(conf)
    return write_config(config_dir, "global", merged)


def read_config(config_dir: Optional[str], name: str) -> Dict[str, Any]:
    if config_dir is None:
        return {}
    from ..utils.store_backend import backend_for

    backend = backend_for(config_dir)
    path = _config_path(config_dir, name)
    try:
        return json.loads(backend.read_bytes(path).decode())
    except FileNotFoundError:
        return {}


def global_config(config_dir: Optional[str]) -> Dict[str, Any]:
    conf = dict(DEFAULT_GLOBAL_CONFIG)
    conf.update(read_config(config_dir, "global"))
    return conf


def task_config(
    config_dir: Optional[str], task_name: str, defaults: Optional[Dict[str, Any]] = None
) -> Dict[str, Any]:
    conf = dict(DEFAULT_TASK_CONFIG)
    if defaults:
        conf.update(defaults)
    conf.update(read_config(config_dir, task_name))
    return conf
