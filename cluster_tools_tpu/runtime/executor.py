"""Execution backends — the ``target=`` seam.

The reference fans per-block work out as independent scheduler processes
(Slurm ``sbatch`` / LSF ``bsub`` / local ProcessPool — reference
cluster_tasks.py:388-624).  On TPU the unit of dispatch is a *device program*, not a
process, so the backends here are:

  * ``local`` — host loop (optionally a thread pool for IO overlap); runs the same
    kernels on whatever the default jax backend is.  This is the parity oracle.
  * ``tpu``   — prefers a task's ``process_block_batch``: blocks are grouped into
    fixed-size batches (static shapes for XLA), padded, and executed as one jit
    dispatch, vmapped over the batch and — when several devices are visible —
    sharded over a ``jax.sharding.Mesh`` by the task's kernels.  Tasks that
    additionally implement the split ``read_batch`` / ``compute_batch`` /
    ``write_batch`` protocol run under an explicit three-stage pipeline
    (read pool → serialized compute → write pool, bounded to
    ``pipeline_depth`` batches per stage), so chunk reads of batch i+1 and
    chunk writes of batch i−1 both hide behind batch i's device program.

Both report per-block success/failure so the task layer can retry exactly the
failed blocks.

The split protocol has a cross-TASK generalization in ``runtime/stream.py``
(ctt-stream): a workflow-declared FusedChain runs several split-protocol
tasks as one streaming pass — one read per slab, all compute stages on
device, elided intermediates never reach the store.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import traceback
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Any, Dict, List, Sequence, Tuple

from .. import faults
from ..obs import heartbeat as obs_heartbeat
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..utils.blocking import Blocking

RunResult = Tuple[List[int], List[int], Dict[int, str]]  # done, failed, errors


def block_deadline_s(config: Dict[str, Any]) -> float:
    """Per-block soft deadline in seconds (0 = watchdog off): the
    ``block_deadline_s`` config key, else ``CTT_BLOCK_DEADLINE_S``;
    malformed values degrade to off like every other CTT_* switch.

    "Soft" because Python cannot kill a thread: a block that exceeds the
    deadline is *recorded failed* (``executor.blocks_timed_out``) and fed
    to the task retry loop, while the hung call is left to finish in the
    background — idempotent blocks make the possible late completion
    harmless (the same contract block retry already relies on)."""
    raw = config.get("block_deadline_s")
    if raw is None:
        raw = os.environ.get("CTT_BLOCK_DEADLINE_S")
    try:
        deadline = float(raw) if raw is not None else 0.0
    except (TypeError, ValueError):
        deadline = 0.0
    return max(deadline, 0.0)


def _record(task, label: str, n_blocks: int, seconds: float) -> None:
    rec = getattr(task, "record_timing", None)
    if rec is not None:
        rec(label, n_blocks, seconds)


def stacked_dispatch(task, compute_fn, payload, blocking, config,
                     all_ids: List[int], fused: bool):
    """ONE guarded device dispatch over a (possibly stacked) payload —
    the compute core of the staged pipeline's dispatch group, shared
    with the ctt-microbatch job-batch runner (serve/microbatch.py),
    which lifts the same ``stack_payloads``/``unstack_results`` contract
    from block batches to whole jobs.  Same fault site
    (``executor.stage_compute``), same span shape, same dispatch
    counters — obs watch and the chip-mode accounting see a job-stacked
    dispatch exactly like an hbm-stacked one.  The hbm use_guard pins
    evicted-entry deletes past the dispatch (a concurrent serve job's
    eviction must not free buffers an in-flight program still reads)."""
    from . import hbm

    faults.check("executor.stage_compute", id=all_ids[0])
    with obs_trace.span(
        "stage_compute", kind="device", task=task.identifier,
        blocks=len(all_ids), block_ids=list(all_ids),
    ), hbm.use_guard():
        result = compute_fn(payload, blocking, config)
    obs_metrics.inc("device.dispatches")
    if fused:
        obs_metrics.inc("device.fused_blocks", len(all_ids))
    return result


def profiler_trace(config: Dict[str, Any]):
    """jax profiler context when the ``profile_dir`` config knob is set:
    dispatches inside are captured as a TensorBoard/XPlane trace
    (SURVEY.md §5 — the reference has log-derived timing only; device traces
    are the strictly-additive TPU upgrade)."""
    profile_dir = config.get("profile_dir")
    if not profile_dir:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.trace(profile_dir)


def resolve_batch_size(config: Dict[str, Any]) -> int:
    """Blocks per device dispatch: the ``device_batch_size`` config knob,
    else the measured pin (CTT_DEVICE_BATCH / chip pin file), else the
    backend-aware default — times the visible device count.  Shared by the
    TpuExecutor and the fused-chain runner (ctt-stream) so a fused and an
    unfused run chunk the block list identically."""
    bs_conf = config.get("device_batch_size")
    if bs_conf is None:
        # measured pin (env var, else the backend-tagged pin file —
        # tools/chip_session.py writes CTT_DEVICE_BATCH), else the
        # backend-aware default; malformed pins degrade to the default
        # like every other CTT_* switch
        from ..ops import _backend

        pin = _backend.pinned_value("CTT_DEVICE_BATCH")
        try:
            bs_conf = int(pin)
        except (TypeError, ValueError):
            import jax

            # backend-aware default: see runtime/config.py
            bs_conf = 1 if jax.default_backend() == "cpu" else 8
    batch_size = max(int(bs_conf), 1)
    devices = config.get("devices")
    if devices and devices != "global":
        n_dev = len(devices)
    else:
        # resolved once per process via the execution context (ctt-serve):
        # a long-lived daemon dispatches thousands of batches and must not
        # re-query the backend for a constant on each one
        from .workflow import ExecutionContext

        n_dev = ExecutionContext.process_context().local_device_count()
    return batch_size * n_dev


class BaseExecutor:
    name = "base"

    def __init__(self, config: Dict[str, Any]):
        self.config = config
        # ctt-watch: every executing process (driver dispatch loop, or the
        # LocalExecutor inside a scheduler worker) heartbeats while it
        # owns blocks; one global check + no thread when tracing is off
        obs_heartbeat.ensure_started()

    def run_blocks(
        self, task, blocking: Blocking, block_ids: Sequence[int], config: Dict[str, Any]
    ) -> RunResult:  # pragma: no cover - abstract
        raise NotImplementedError


class LocalExecutor(BaseExecutor):
    """Host loop / thread pool over ``process_block``."""

    name = "local"

    def run_blocks(self, task, blocking, block_ids, config) -> RunResult:
        n_workers = max(int(config.get("max_jobs", 1)), 1)
        if not getattr(task, "pipeline_safe", True):
            # same contract as the TpuExecutor pipeline: blocks that read
            # regions concurrent blocks write (two-pass pass 2) run serially
            # so the visible neighbor labels are not timing-dependent
            n_workers = 1
        done: List[int] = []
        failed: List[int] = []
        errors: Dict[int, str] = {}

        durations: List[float] = []

        def _one(bid: int):
            obs_heartbeat.note_block_start(bid)
            try:
                faults.check("executor.block", id=bid)
                t0 = time.perf_counter()
                # explicit task= attribute: under a thread pool the span
                # opens in a worker thread where the per-thread parent
                # stack cannot see the enclosing task span
                with obs_trace.span(
                    "block", kind="host", task=task.identifier, block=bid
                ):
                    task.process_block(bid, blocking, config)
                durations.append(time.perf_counter() - t0)
                obs_heartbeat.note_blocks_done()
                return bid, None
            except Exception:
                obs_heartbeat.note_blocks_failed()
                return bid, traceback.format_exc()
            finally:
                obs_heartbeat.note_block_end(bid)

        deadline = block_deadline_s(config)
        with profiler_trace(config):
            if deadline > 0:
                results = self._run_with_watchdog(
                    _one, block_ids, n_workers, deadline
                )
            elif n_workers == 1:
                results = [_one(b) for b in block_ids]
            else:
                with ThreadPoolExecutor(n_workers) as pool:
                    results = list(pool.map(_one, block_ids))
        if durations:
            # one aggregate record per dispatch round: a per-block record
            # would make the status JSON O(n_blocks) at production scale
            _record(task, "blocks_total", len(durations), sum(durations))
            _record(task, "block_max", 1, max(durations))
        for bid, err in results:
            if err is None:
                done.append(bid)
            else:
                failed.append(bid)
                errors[bid] = err
        return done, failed, errors

    @staticmethod
    def _run_with_watchdog(fn, block_ids, n_workers: int, deadline: float):
        """Run ``fn(bid) -> (bid, err)`` per block under the soft-deadline
        watchdog: a block that doesn't resolve within ``deadline`` seconds
        is converted into a failed block (the task retry loop re-runs it)
        instead of hanging the dispatch.  Always pool-based (even at one
        worker) so the waiter can abandon a hung call; the pool is shut
        down without joining — hung threads are left to finish in the
        background (see :func:`block_deadline_s`)."""
        pool = ThreadPoolExecutor(
            max(n_workers, 1), thread_name_prefix="ctt-watchdog"
        )
        results = []
        try:
            futures = [(bid, pool.submit(fn, bid)) for bid in block_ids]
            for bid, fut in futures:
                try:
                    results.append(fut.result(timeout=deadline))
                except FutureTimeout:
                    # not-yet-started blocks behind a hung worker cancel
                    # cleanly; running ones are abandoned to the background
                    fut.cancel()
                    obs_metrics.inc("executor.blocks_timed_out")
                    results.append((
                        bid,
                        f"block {bid} exceeded the soft deadline "
                        f"({deadline:.1f}s) — recorded failed for retry; "
                        "the hung call is left to finish in the background",
                    ))
                except Exception:
                    # fn reports its own errors; this only guards cancelled
                    # futures racing the result() call
                    results.append((bid, traceback.format_exc()))
        finally:
            pool.shutdown(wait=False)
        return results


class TpuExecutor(BaseExecutor):
    """Batched device dispatch: group blocks, let the task jit over the batch."""

    name = "tpu"

    def run_blocks(self, task, blocking, block_ids, config) -> RunResult:
        batch_fn = getattr(task, "process_block_batch", None)
        if batch_fn is None:
            return LocalExecutor(self.config).run_blocks(
                task, blocking, block_ids, config
            )

        batch_size = resolve_batch_size(config)

        done: List[int] = []
        failed: List[int] = []
        errors: Dict[int, str] = {}
        ids = list(block_ids)
        trace = profiler_trace(config)
        with trace:
            self._run_batches(
                task, blocking, config, ids, batch_size, batch_fn,
                done, failed, errors,
            )
        return done, failed, errors

    @staticmethod
    def _staged_fns(task):
        """The split batch protocol: a task that implements all of
        ``read_batch`` / ``compute_batch`` / ``write_batch`` opts into the
        three-stage pipeline; ``process_block_batch`` stays the monolithic
        composition (used at depth 1 and by the per-block fallback)."""
        fns = tuple(
            getattr(task, name, None)
            for name in ("read_batch", "compute_batch", "write_batch")
        )
        return fns if all(fns) else None

    def _per_block_fallback(
        self, task, blocking, config, chunk, done, failed, errors, tb
    ) -> None:
        """Re-run a failed batch block by block so a single poisoned block
        doesn't fail the whole batch."""
        from . import hbm

        for bid in chunk:
            try:
                with obs_trace.span(
                    "block_fallback", kind="host",
                    task=task.identifier, block=bid,
                ), hbm.use_guard():
                    task.process_block(bid, blocking, config)
                done.append(bid)
                obs_heartbeat.note_blocks_done()
            except Exception:
                failed.append(bid)
                errors[bid] = traceback.format_exc()
                obs_heartbeat.note_blocks_failed()
        if not any(b in errors for b in chunk):
            # batch path is broken but every block succeeded per-block;
            # surface why without mislabeling a done block as failed
            print(
                f"[{self.name}] batch dispatch failed, per-block fallback "
                f"succeeded for blocks {chunk[0]}..{chunk[-1]}:\n{tb}"
            )

    def _run_batches(
        self, task, blocking, config, ids, batch_size, batch_fn,
        done, failed, errors,
    ) -> None:
        from ..parallel.dispatch import form_batches
        from . import hbm

        chunks = form_batches(ids, batch_size)

        # ctt-hbm aggregated dispatch for MONOLITHIC tasks: the staged
        # pipeline fuses hbm_stack read payloads into one device program
        # (_run_staged), but a task exposing only process_block_batch (the
        # inference path) used to be stuck at batch_size blocks/dispatch.
        # Its batch fn stacks whatever id list it is handed, so handing it
        # hbm_stack consecutive chunks IS the aggregated dispatch — same
        # blocks, same order, fewer, larger programs.  The per-block
        # fallback grain is unchanged (a failed fused batch degrades block
        # by block, exactly like an unfused one).
        stack_n = hbm.hbm_stack(config)
        if self._staged_fns(task) is None and stack_n > 1 and len(chunks) > 1:
            chunks = [
                [bid for chunk in chunks[i: i + stack_n] for bid in chunk]
                for i in range(0, len(chunks), stack_n)
            ]

        batch_seconds: List[float] = []  # list.append: safe from pool threads

        def _one_batch(chunk):
            # the batch's first block stands in for the whole batch in the
            # heartbeat's in-flight list (straggler age tracking)
            obs_heartbeat.note_block_start(chunk[0])
            try:
                faults.check("executor.batch", id=chunk[0])
                t0 = time.perf_counter()
                # block_ids lets the live reader attribute the batch wall
                # to each block (the spatial latency heatmap)
                # the guard pins evicted-entry deletes past this dispatch
                # (a concurrent serve job's eviction must not free buffers
                # an in-flight batch still reads — runtime/hbm.py)
                with obs_trace.span(
                    "block_batch", kind="device", task=task.identifier,
                    blocks=len(chunk), block_ids=list(chunk),
                ), hbm.use_guard():
                    batch_fn(chunk, blocking, config)
                obs_metrics.inc("device.dispatches")
                if len(chunk) > batch_size:
                    # blocks that rode a fused (aggregated) dispatch —
                    # the hbm_stack economics, monolithic-path edition
                    obs_metrics.inc("device.fused_blocks", len(chunk))
                dt = time.perf_counter() - t0
                batch_seconds.append(dt)
                _record(
                    task,
                    f"batch_{chunk[0]}_{chunk[-1]}",
                    len(chunk),
                    dt,
                )
                done.extend(chunk)
                obs_heartbeat.note_blocks_done(len(chunk))
            except Exception:
                self._per_block_fallback(
                    task, blocking, config, chunk, done, failed, errors,
                    traceback.format_exc(),
                )
            finally:
                obs_heartbeat.note_block_end(chunk[0])

        # Batch pipelining (the reference's dask IO/compute overlap,
        # inference.py:319-327, moved into the executor).  A task whose
        # blocks read regions other blocks of the SAME dispatch write (e.g.
        # two-pass pass 2: the halo'd read overlaps a same-color *diagonal*
        # neighbor's inner box) declares ``pipeline_safe = False`` — chunk
        # writes are atomic (os.replace), so concurrency would not tear
        # data, but it would make which neighbor labels a batch sees
        # timing-dependent; depth 1 (the strictly serial loop) keeps the
        # output deterministic.
        #
        # Two pipelined forms, best first:
        #   * tasks implementing the split protocol (``_staged_fns``) run a
        #     true three-stage pipeline: a read pool prefetches batch i+1's
        #     chunks, the dispatching thread runs every device program IN
        #     ORDER (deterministic dispatch), and a write pool drains batch
        #     i−1's chunk encodes — reads AND writes both overlap compute;
        #   * monolithic ``process_block_batch`` tasks keep the depth-d
        #     thread pool (whole batches overlap).
        depth = max(int(config.get("pipeline_depth", 2)), 1)
        if not getattr(task, "pipeline_safe", True):
            depth = 1
        staged = self._staged_fns(task)
        t_wall0 = time.perf_counter()
        if depth == 1 or len(chunks) == 1:
            for chunk in chunks:
                _one_batch(chunk)
        elif staged is not None:
            self._run_staged(
                task, blocking, config, chunks, depth, staged,
                done, failed, errors, batch_seconds,
            )
        else:
            with ThreadPoolExecutor(depth) as pool:
                list(pool.map(_one_batch, chunks))
        # pipeline overlap efficiency: with depth > 1, summed in-flight
        # batch seconds exceeding the dispatch wall is exactly the host-IO
        # time hidden behind device execution
        obs_metrics.inc("executor.batches", len(chunks))
        obs_metrics.inc("executor.batch_s", sum(batch_seconds))
        obs_metrics.inc(
            "executor.dispatch_wall_s", time.perf_counter() - t_wall0
        )

    def _run_staged(
        self, task, blocking, config, chunks, depth, staged,
        done, failed, errors, batch_seconds,
    ) -> None:
        """Three-stage pipeline: read → device compute → write over bounded
        in-flight deques (the explicit-stage successor of the depth-N
        read→compute→write pool).

        Up to ``depth`` reads and ``depth`` writes ride small thread pools
        while the calling thread is the ONE compute stage, consuming read
        results in submission order — so the device sees the exact dispatch
        sequence of the serial loop while batch i+1's chunk decodes and
        batch i−1's chunk encodes both happen under batch i's program (XLA
        releases the GIL during execution).  A stage failure for a batch
        degrades that batch to the per-block fallback; other batches are
        unaffected.

        Async prefetch (ctt-cloud): tasks exposing ``prefetch_batch``
        additionally get a lookahead stage that warms the decoded-chunk
        LRU up to ``depth`` batches BEYOND the read stage's own window —
        chunk fetches overlap as many concurrent range requests as the
        store backend allows instead of one blocking slice per read
        thread, so the read stage of a high-latency object store degrades
        to LRU hits.  Prefetch is advisory (failures surface on the real
        read) and disabled with ``prefetch: false``.

        ctt-hbm adds two device-side levers on the same skeleton:

          * **aggregated dispatch** — with ``hbm_stack: k`` (or
            ``CTT_HBM_STACK``) and a task implementing
            ``stack_payloads``/``unstack_results``, up to ``k``
            consecutive read payloads concatenate into ONE ``(sum_B,
            ...)`` stacked device dispatch (the coarse-CC ``(n_tiles,
            ...)`` shape generalized); results split back per batch for
            the write pool, so host IO granularity is unchanged while
            dispatch count drops k-fold.  Kernels are vmapped over the
            leading axis — the stacked dispatch is byte-identical to the
            per-batch (and per-block) path, which remains the fallback.
          * **double-buffered device prefetch** — tasks exposing
            ``upload_batch`` get a transfer stage between read and
            compute: while batch k's device program runs, batch k+1's
            host arrays are already crossing to HBM on a transfer
            thread, bounded to ``runtime.hbm.UPLOAD_SLOTS`` (2) in-flight
            uploads (the same process-wide gate interleaves two serve
            jobs' uploads at ``concurrency > 1``).  Disabled together
            with the prefetch lookahead by ``prefetch: false``."""
        read_fn, compute_fn, write_fn = staged
        from . import hbm

        stage_s = {"read": 0.0, "compute": 0.0, "write": 0.0,
                   "prefetch": 0.0, "upload": 0.0}
        acc_lock = threading.Lock()

        stack_n = 1
        stack_fn = getattr(task, "stack_payloads", None)
        unstack_fn = getattr(task, "unstack_results", None)
        if stack_fn is not None and unstack_fn is not None:
            stack_n = hbm.hbm_stack(config)
        upload_fn = getattr(task, "upload_batch", None)
        # ``prefetch: false`` opts out of ALL lookahead (the acceptance
        # switch restoring pre-hbm execution together with
        # CTT_HBM_CACHE_MB=0); ``hbm_prefetch: false`` disables only the
        # device transfer stage, leaving the ctt-cloud LRU prefetch alone
        # (the honest A/B baseline for the hbm bench)
        if not config.get("prefetch", True) or not config.get(
            "hbm_prefetch", True
        ):
            upload_fn = None

        def _acc(stage: str, dt: float) -> None:
            with acc_lock:
                stage_s[stage] += dt

        def _read(chunk):
            obs_heartbeat.note_block_start(chunk[0])
            faults.check("executor.stage_read", id=chunk[0])
            t0 = time.perf_counter()
            with obs_trace.span(
                "stage_read", kind="host_io", task=task.identifier,
                blocks=len(chunk), block_ids=list(chunk),
            ):
                payload = read_fn(chunk, blocking, config)
            _acc("read", time.perf_counter() - t0)
            return payload

        def _write(chunk, result):
            faults.check("executor.stage_write", id=chunk[0])
            t0 = time.perf_counter()
            with obs_trace.span(
                "stage_write", kind="host_io", task=task.identifier,
                blocks=len(chunk), block_ids=list(chunk),
            ):
                write_fn(result, blocking, config)
            _acc("write", time.perf_counter() - t0)

        prefetch_fn = getattr(task, "prefetch_batch", None)
        if not config.get("prefetch", True):
            prefetch_fn = None

        def _prefetch(chunk):
            t0 = time.perf_counter()
            try:
                with obs_trace.span(
                    "stage_prefetch", kind="host_io", task=task.identifier,
                    blocks=len(chunk), block_ids=list(chunk),
                ):
                    prefetch_fn(chunk, blocking, config)
                obs_metrics.inc("executor.prefetch_batches")
            except Exception:  # ctt: noqa[CTT009] prefetch is advisory — the read stage re-raises and classifies any real failure
                pass
            _acc("prefetch", time.perf_counter() - t0)

        n_blocks = sum(len(c) for c in chunks)
        reads: deque = deque()    # (chunk, Future[payload])
        uploads: deque = deque()  # (group, counts, Future[payload])
        writes: deque = deque()   # (chunk, Future[None], t_batch0)
        with ThreadPoolExecutor(
            depth, thread_name_prefix="ctt-read"
        ) as read_pool, ThreadPoolExecutor(
            depth, thread_name_prefix="ctt-write"
        ) as write_pool, ThreadPoolExecutor(
            depth, thread_name_prefix="ctt-prefetch-stage"
        ) as prefetch_pool, ThreadPoolExecutor(
            1, thread_name_prefix="ctt-hbm-upload"
        ) as upload_pool:
            # lookahead frontier: the first ``depth`` chunks go straight
            # to the read pool (prefetching them would double-fetch), so
            # the prefetch stage starts ``depth`` ahead and stays ``depth``
            # beyond the read window throughout
            next_prefetch = depth

            def _advance_prefetch(upto: int) -> None:
                nonlocal next_prefetch
                if prefetch_fn is None:
                    return
                while next_prefetch < min(upto, len(chunks)):
                    prefetch_pool.submit(_prefetch, chunks[next_prefetch])
                    next_prefetch += 1

            def _drain_write():
                chunk, fut, t_batch0 = writes.popleft()
                try:
                    fut.result()
                except Exception:
                    self._per_block_fallback(
                        task, blocking, config, chunk, done, failed,
                        errors, traceback.format_exc(),
                    )
                    obs_heartbeat.note_block_end(chunk[0])
                    return
                batch_seconds.append(time.perf_counter() - t_batch0)
                done.extend(chunk)
                obs_heartbeat.note_blocks_done(len(chunk))
                obs_heartbeat.note_block_end(chunk[0])

            def _fallback_group(group):
                # called from an except block: every batch of the failed
                # dispatch group degrades to the per-block path
                for chunk in group:
                    self._per_block_fallback(
                        task, blocking, config, chunk, done, failed,
                        errors, traceback.format_exc(),
                    )
                    obs_heartbeat.note_block_end(chunk[0])

            def _upload(payload):
                # transfer thread (ctt-hbm): batch k+1 crosses to HBM
                # while batch k's device program runs
                t0 = time.perf_counter()
                out = upload_fn(payload, blocking, config)
                _acc("upload", time.perf_counter() - t0)
                return out

            def _compute_group(group, counts, payload):
                all_ids = [b for c in group for b in c]
                t_batch0 = time.perf_counter()
                try:
                    t0 = time.perf_counter()
                    result = stacked_dispatch(
                        task, compute_fn, payload, blocking, config,
                        all_ids, fused=len(group) > 1,
                    )
                    dt = time.perf_counter() - t0
                    _acc("compute", dt)
                    _record(task, f"batch_{all_ids[0]}_{all_ids[-1]}",
                            len(all_ids), dt)
                    results = (
                        unstack_fn(result, counts, blocking, config)
                        if len(group) > 1 else [result]
                    )
                except Exception:
                    _fallback_group(group)
                    return
                for chunk, res in zip(group, results):
                    writes.append(
                        (chunk, write_pool.submit(_write, chunk, res),
                         t_batch0)
                    )
                while len(writes) > depth:
                    _drain_write()

            def _drain_upload():
                group, counts, fut = uploads.popleft()
                try:
                    payload = fut.result()
                except Exception:
                    _fallback_group(group)
                    return
                _compute_group(group, counts, payload)

            def _consume():
                """Form one dispatch group (up to ``stack_n`` read
                payloads, stacked) and move it down the pipeline — the
                upload stage when armed, else straight to compute.  The
                deques are FIFO throughout, so the device sees the exact
                dispatch sequence of the serial loop."""
                group, payloads = [], []
                while reads and len(group) < stack_n:
                    chunk, fut = reads.popleft()
                    try:
                        payloads.append(fut.result())
                        group.append(chunk)
                    except Exception:
                        self._per_block_fallback(
                            task, blocking, config, chunk, done, failed,
                            errors, traceback.format_exc(),
                        )
                        obs_heartbeat.note_block_end(chunk[0])
                if not group:
                    return
                counts = [len(c) for c in group]
                try:
                    payload = (
                        stack_fn(payloads, blocking, config)
                        if len(group) > 1 else payloads[0]
                    )
                except Exception:
                    _fallback_group(group)
                    return
                if upload_fn is None:
                    _compute_group(group, counts, payload)
                    return
                uploads.append(
                    (group, counts, upload_pool.submit(_upload, payload))
                )
                while len(uploads) >= hbm.UPLOAD_SLOTS:
                    _drain_upload()

            t_wall0 = time.perf_counter()
            for i, chunk in enumerate(chunks):
                _advance_prefetch(i + 1 + depth)
                reads.append((chunk, read_pool.submit(_read, chunk)))
                while len(reads) >= max(depth, stack_n):
                    _consume()
            while reads:
                _consume()
            while uploads:
                _drain_upload()
            while writes:
                _drain_write()
        wall = time.perf_counter() - t_wall0

        # one aggregate record per stage per dispatch round (per-batch
        # stage records would make the status JSON O(n_batches) × 3); the
        # per-batch compute walls above keep the task_breakdown contract
        _record(task, "stage_read_total", n_blocks, stage_s["read"])
        _record(task, "stage_compute_total", n_blocks, stage_s["compute"])
        _record(task, "stage_write_total", n_blocks, stage_s["write"])
        obs_metrics.inc("executor.stage_batches", len(chunks))
        obs_metrics.inc("executor.stage_read_s", stage_s["read"])
        obs_metrics.inc("executor.stage_compute_s", stage_s["compute"])
        obs_metrics.inc("executor.stage_write_s", stage_s["write"])
        obs_metrics.inc("executor.stage_prefetch_s", stage_s["prefetch"])
        obs_metrics.inc("executor.stage_upload_s", stage_s["upload"])
        # IO seconds the pipeline hid behind (serialized) compute: summed
        # read+write stage time minus the wall the compute stage left open
        obs_metrics.inc(
            "executor.stage_hidden_io_s",
            max(
                0.0,
                stage_s["read"] + stage_s["write"]
                - max(0.0, wall - stage_s["compute"]),
            ),
        )



_EXECUTORS = {
    "local": LocalExecutor,
    "tpu": TpuExecutor,
}


def get_executor(target: str, config: Dict[str, Any]) -> BaseExecutor:
    if target not in _EXECUTORS:
        # the batch-scheduler backends register on import
        from . import cluster_executor  # noqa: F401
    try:
        return _EXECUTORS[target](config)
    except KeyError:
        raise ValueError(
            f"unknown target {target!r}; available: {sorted(_EXECUTORS)}"
        ) from None


def register_executor(name: str, cls) -> None:
    """Seam for additional backends (the reference's slurm/lsf equivalents)."""
    _EXECUTORS[name] = cls
