"""Execution backends — the ``target=`` seam.

The reference fans per-block work out as independent scheduler processes
(Slurm ``sbatch`` / LSF ``bsub`` / local ProcessPool — reference
cluster_tasks.py:388-624).  On TPU the unit of dispatch is a *device program*, not a
process, so the backends here are:

  * ``local`` — host loop (optionally a thread pool for IO overlap); runs the same
    kernels on whatever the default jax backend is.  This is the parity oracle.
  * ``tpu``   — prefers a task's ``process_block_batch``: blocks are grouped into
    fixed-size batches (static shapes for XLA), padded, and executed as one jit
    dispatch, vmapped over the batch and — when several devices are visible —
    sharded over a ``jax.sharding.Mesh`` by the task's kernels.

Both report per-block success/failure so the task layer can retry exactly the
failed blocks.
"""

from __future__ import annotations

import contextlib
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Sequence, Tuple

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..utils.blocking import Blocking

RunResult = Tuple[List[int], List[int], Dict[int, str]]  # done, failed, errors


def _record(task, label: str, n_blocks: int, seconds: float) -> None:
    rec = getattr(task, "record_timing", None)
    if rec is not None:
        rec(label, n_blocks, seconds)


def profiler_trace(config: Dict[str, Any]):
    """jax profiler context when the ``profile_dir`` config knob is set:
    dispatches inside are captured as a TensorBoard/XPlane trace
    (SURVEY.md §5 — the reference has log-derived timing only; device traces
    are the strictly-additive TPU upgrade)."""
    profile_dir = config.get("profile_dir")
    if not profile_dir:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.trace(profile_dir)


class BaseExecutor:
    name = "base"

    def __init__(self, config: Dict[str, Any]):
        self.config = config

    def run_blocks(
        self, task, blocking: Blocking, block_ids: Sequence[int], config: Dict[str, Any]
    ) -> RunResult:  # pragma: no cover - abstract
        raise NotImplementedError


class LocalExecutor(BaseExecutor):
    """Host loop / thread pool over ``process_block``."""

    name = "local"

    def run_blocks(self, task, blocking, block_ids, config) -> RunResult:
        n_workers = max(int(config.get("max_jobs", 1)), 1)
        if not getattr(task, "pipeline_safe", True):
            # same contract as the TpuExecutor pipeline: blocks that read
            # regions concurrent blocks write (two-pass pass 2) run serially
            # so the visible neighbor labels are not timing-dependent
            n_workers = 1
        done: List[int] = []
        failed: List[int] = []
        errors: Dict[int, str] = {}

        durations: List[float] = []

        def _one(bid: int):
            try:
                t0 = time.perf_counter()
                # explicit task= attribute: under a thread pool the span
                # opens in a worker thread where the per-thread parent
                # stack cannot see the enclosing task span
                with obs_trace.span(
                    "block", kind="host", task=task.identifier, block=bid
                ):
                    task.process_block(bid, blocking, config)
                durations.append(time.perf_counter() - t0)
                return bid, None
            except Exception:
                return bid, traceback.format_exc()

        with profiler_trace(config):
            if n_workers == 1:
                results = [_one(b) for b in block_ids]
            else:
                with ThreadPoolExecutor(n_workers) as pool:
                    results = list(pool.map(_one, block_ids))
        if durations:
            # one aggregate record per dispatch round: a per-block record
            # would make the status JSON O(n_blocks) at production scale
            _record(task, "blocks_total", len(durations), sum(durations))
            _record(task, "block_max", 1, max(durations))
        for bid, err in results:
            if err is None:
                done.append(bid)
            else:
                failed.append(bid)
                errors[bid] = err
        return done, failed, errors


class TpuExecutor(BaseExecutor):
    """Batched device dispatch: group blocks, let the task jit over the batch."""

    name = "tpu"

    def run_blocks(self, task, blocking, block_ids, config) -> RunResult:
        batch_fn = getattr(task, "process_block_batch", None)
        if batch_fn is None:
            return LocalExecutor(self.config).run_blocks(
                task, blocking, block_ids, config
            )

        bs_conf = config.get("device_batch_size")
        if bs_conf is None:
            # measured pin (env var, else the backend-tagged pin file —
            # tools/chip_session.py writes CTT_DEVICE_BATCH), else the
            # backend-aware default; malformed pins degrade to the default
            # like every other CTT_* switch
            from ..ops import _backend

            pin = _backend.pinned_value("CTT_DEVICE_BATCH")
            try:
                bs_conf = int(pin)
            except (TypeError, ValueError):
                import jax

                # backend-aware default: see runtime/config.py
                bs_conf = 1 if jax.default_backend() == "cpu" else 8
        batch_size = max(int(bs_conf), 1)
        n_dev = self._n_devices(config)
        batch_size *= n_dev

        done: List[int] = []
        failed: List[int] = []
        errors: Dict[int, str] = {}
        ids = list(block_ids)
        trace = profiler_trace(config)
        with trace:
            self._run_batches(
                task, blocking, config, ids, batch_size, batch_fn,
                done, failed, errors,
            )
        return done, failed, errors

    def _run_batches(
        self, task, blocking, config, ids, batch_size, batch_fn,
        done, failed, errors,
    ) -> None:
        chunks = [
            ids[i : i + batch_size] for i in range(0, len(ids), batch_size)
        ]

        batch_seconds: List[float] = []  # list.append: safe from pool threads

        def _one_batch(chunk):
            try:
                t0 = time.perf_counter()
                with obs_trace.span(
                    "block_batch", kind="device", task=task.identifier,
                    blocks=len(chunk),
                ):
                    batch_fn(chunk, blocking, config)
                dt = time.perf_counter() - t0
                batch_seconds.append(dt)
                _record(
                    task,
                    f"batch_{chunk[0]}_{chunk[-1]}",
                    len(chunk),
                    dt,
                )
                done.extend(chunk)
            except Exception:
                tb = traceback.format_exc()
                # fall back to per-block execution so a single poisoned block
                # doesn't fail the whole batch
                for bid in chunk:
                    try:
                        with obs_trace.span(
                            "block_fallback", kind="host",
                            task=task.identifier, block=bid,
                        ):
                            task.process_block(bid, blocking, config)
                        done.append(bid)
                    except Exception:
                        failed.append(bid)
                        errors[bid] = traceback.format_exc()
                if not any(b in errors for b in chunk):
                    # batch path is broken but every block succeeded per-block;
                    # surface why without mislabeling a done block as failed
                    print(
                        f"[{self.name}] batch dispatch failed, per-block fallback "
                        f"succeeded for blocks {chunk[0]}..{chunk[-1]}:\n{tb}"
                    )

        # Batch pipelining (the reference's dask IO/compute overlap,
        # inference.py:319-327, moved into the executor): with depth d, up to d
        # batches are in flight on a small thread pool, so batch i+1's host
        # chunk reads/decodes run while batch i's device program executes
        # (XLA releases the GIL during execution).  Depth 1 restores the
        # serial loop.  A task whose blocks read regions other blocks of the
        # SAME dispatch write (e.g. two-pass pass 2: the halo'd read overlaps
        # a same-color *diagonal* neighbor's inner box) declares
        # ``pipeline_safe = False`` — chunk writes are atomic (os.replace),
        # so concurrency would not tear data, but it would make which
        # neighbor labels a batch sees timing-dependent; serial batches keep
        # the output deterministic.
        depth = max(int(config.get("pipeline_depth", 2)), 1)
        if not getattr(task, "pipeline_safe", True):
            depth = 1
        t_wall0 = time.perf_counter()
        if depth == 1 or len(chunks) == 1:
            for chunk in chunks:
                _one_batch(chunk)
        else:
            with ThreadPoolExecutor(depth) as pool:
                list(pool.map(_one_batch, chunks))
        # pipeline overlap efficiency: with depth > 1, summed in-flight
        # batch seconds exceeding the dispatch wall is exactly the host-IO
        # time hidden behind device execution
        obs_metrics.inc("executor.batches", len(chunks))
        obs_metrics.inc("executor.batch_s", sum(batch_seconds))
        obs_metrics.inc(
            "executor.dispatch_wall_s", time.perf_counter() - t_wall0
        )

    @staticmethod
    def _n_devices(config) -> int:
        devices = config.get("devices")
        if devices:
            return len(devices)
        try:
            import jax

            return jax.local_device_count()
        except Exception:  # pragma: no cover
            return 1


_EXECUTORS = {
    "local": LocalExecutor,
    "tpu": TpuExecutor,
}


def get_executor(target: str, config: Dict[str, Any]) -> BaseExecutor:
    if target not in _EXECUTORS:
        # the batch-scheduler backends register on import
        from . import cluster_executor  # noqa: F401
    try:
        return _EXECUTORS[target](config)
    except KeyError:
        raise ValueError(
            f"unknown target {target!r}; available: {sorted(_EXECUTORS)}"
        ) from None


def register_executor(name: str, cls) -> None:
    """Seam for additional backends (the reference's slurm/lsf equivalents)."""
    _EXECUTORS[name] = cls
