"""ctt-fault: deterministic, seeded fault injection for the block pipeline.

The runtime's whole reliability story is "blocks are idempotent, rerun the
failed ones" (runtime/task.py retry loop, peer-wait timeouts, abort flags) —
this package makes those paths *exercisable*: named injection sites threaded
through the storage, executor, cluster, task, and collective layers fire
deterministic faults according to a seeded spec, so chaos runs are
reproducible and diffable (every injected event lands in obs metrics and the
span stream).

Spec grammar (``CTT_FAULTS`` environment variable)::

    CTT_FAULTS = entry (";" entry)*
    entry      = "seed=" int
               | site ":" action [":" param ("," param)*]
    param      = "p=" float        probability per matching check (seeded RNG)
               | "ids=" int("|"int)*   only fire for these ctx ids (job/block)
               | "once"            fire at most once (== times=1)
               | "times=" int      fire at most N times
               | "after=" int      skip the first N matching checks
               | "s=" float        stall duration seconds (stall action)
               | "bytes=" int      torn-payload keep-bytes (torn action)

Example::

    CTT_FAULTS="store.write:io_error:p=0.05;worker.job:kill:ids=1;collective.init:fail:once;seed=42"

Sites (each named where the corresponding code path lives):

  ``store.read`` / ``store.write`` / ``store.decode``  — utils/store.py chunk
      IO; ``store.write`` additionally supports the ``torn`` action, which
      truncates the chunk payload on disk (torn-write simulation) and raises
      ``CorruptChunk`` so the shared retry / block-retry machinery rewrites it.
  ``store.remote_read`` (GET/HEAD) / ``store.remote_write`` (PUT/DELETE)
      — utils/store_backend.py object-store requests (ctt-cloud): one check
      per HTTP round trip, so ``p=`` chaos models a flaky gateway at
      request grain (the request-level retry must absorb it).
  ``store.remote_list``  — utils/store_backend.py listing GETs, one check
      per continuation page: the ctt-ingest watcher's poll primitive —
      chaos here models eventually-visible listings, which the per-page
      retry and the watcher's monotone frontier must absorb.
  ``store.remote_auth``  — utils/store_backend.py request signing
      (ctt-diskless): fires once per signed request, before the
      Authorization header is computed — chaos models credential
      hiccups (expired STS tokens, clock drift 403s), which surface as
      retryable auth errors riding the same request-level retry.
  ``executor.block`` (ctx ``id``: block id) / ``executor.batch`` /
      ``executor.stage_read`` / ``executor.stage_compute`` /
      ``executor.stage_write``  — runtime/executor.py dispatch paths.
  ``worker.job`` (ctx ``id``: job id; before the status write — ``kill``
      simulates a job dying with no status) / ``worker.exit`` (after the
      status write)  — runtime/cluster_worker.py.
  ``task.barrier``  — runtime/task.py peer-wait loop (``stall`` simulates a
      slow peer; ``fail`` a poisoned barrier).
  ``collective.init`` / ``collective.execute``  — parallel/sharded.py entry
      kernels (init failures trigger the graceful sharded→local fallback).
  ``sched.claim`` (ctx ``id``: queue item; between candidate selection and
      the lease link — ``stall`` widens the claim race the link
      arbitrates) / ``sched.write`` (lease payloads; ``torn`` truncates
      the lease JSON — readers age it from file mtime, so a torn lease
      still expires) / ``sched.requeue`` (the expired-lease takeover —
      stale-requeue storms)  — runtime/queue.py (ctt-steal).
  ``fleet.write`` (ctx ``id``: daemon id; fleet heartbeat payloads —
      ``torn`` truncates the ``daemon.<id>.json`` beat, and peer liveness
      readers must degrade to mtime ageing instead of crashing or
      misdeclaring the writer dead)  — serve/fleet.py (ctt-fleet).
  ``fleet.supervisor`` (ctx ``id``: supervisor id; the supervisor's
      decision round, before it observes the fleet — ``kill`` SIGKILLs
      the supervisor mid-burst, the ctt-diskless chaos gate: a restarted
      supervisor must re-adopt the fleet from beats alone)
      — serve/supervisor.py.

Actions: ``io_error`` (OSError EIO), ``fail`` (FaultInjected), ``kill``
(``os._exit(KILL_EXIT_CODE)`` — a hard crash, no cleanup), ``stall``
(sleep ``s`` seconds), ``torn`` (payload truncation, write sites only).

Determinism: every entry owns a ``random.Random`` seeded from the spec seed
and the entry's (site, index), and its stream advances once per *matching*
check — the same spec + seed + call sequence produces the same injection
sequence in any process (tested in tests/test_faults.py).  For faults that
must fire once *across* processes (a killed scheduler job must stay dead
after its resubmission), set ``CTT_FAULT_STATE_DIR``: ``once``/``times``
entries then latch through O_CREAT|O_EXCL files in that directory.

Zero-overhead no-op fast path: with ``CTT_FAULTS`` unset, ``_PLAN`` is None
and every ``check()``/``mangle()`` call is one global load + compare —
nothing is parsed, allocated, or locked (tested by the disabled-overhead
smoke).  A malformed spec raises ``FaultSpecError`` loudly at configure time:
a chaos harness that silently disarms would certify nothing.
"""

from __future__ import annotations

import errno
import os
import random
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "FaultInjected", "FaultSpecError", "check", "mangle", "enabled",
    "configure", "reset", "decision_log", "KILL_EXIT_CODE",
    "ENV_SPEC", "ENV_STATE", "SITE_DOCS", "sites_markdown_table",
]

ENV_SPEC = "CTT_FAULTS"
ENV_STATE = "CTT_FAULT_STATE_DIR"

# hard-crash exit code for the ``kill`` action: distinct from 0/1 so a
# submitter / test can tell an injected kill from an ordinary failure
KILL_EXIT_CODE = 17


class FaultInjected(RuntimeError):
    """Raised by the ``fail`` action (and wrapped by site-local classifiers,
    e.g. the store turns injected decode faults into ``CorruptChunk``)."""


class FaultSpecError(ValueError):
    """Malformed ``CTT_FAULTS`` spec — always loud, never silently disarmed."""


# site -> one-line meaning.  The single source of truth for the injection
# surface: KNOWN_SITES derives from it, README's fault-site table is
# generated from it (sites_markdown_table), and lint rule CTT205 holds
# every entry to >= 1 live call site (and every call-site literal to an
# entry) — the three views cannot drift.
SITE_DOCS: Dict[str, str] = {
    "store.read": "utils/store.py chunk read IO",
    "store.write":
        "utils/store.py chunk write IO (also `torn`: truncated payload)",
    "store.decode": "utils/store.py chunk decompress/decode",
    "store.remote_read":
        "utils/store_backend.py object-store GET/HEAD round trip",
    "store.remote_write":
        "utils/store_backend.py object-store PUT/DELETE round trip",
    "store.remote_list":
        "utils/store_backend.py listing GET page (the ctt-ingest poll)",
    "store.remote_auth":
        "utils/store_backend.py request signing (credential hiccups "
        "surface as retryable auth errors)",
    "executor.block": "runtime/executor.py per-block dispatch (ctx `id`)",
    "executor.batch": "runtime/executor.py block-batch dispatch",
    "executor.stage_read": "runtime/executor.py pipelined read stage",
    "executor.stage_compute": "runtime/executor.py pipelined compute stage",
    "executor.stage_write": "runtime/executor.py pipelined write stage",
    "worker.job":
        "runtime/cluster_worker.py before the status write "
        "(`kill`: job dies with no status)",
    "worker.exit": "runtime/cluster_worker.py after the status write",
    "task.barrier": "runtime/task.py peer-wait loop (`stall`: slow peer)",
    "collective.init":
        "parallel/sharded.py mesh init (failures take the local fallback)",
    "collective.execute": "parallel/sharded.py collective execution",
    "sched.claim":
        "runtime/queue.py between candidate pick and the lease link",
    "sched.write":
        "runtime/queue.py lease payloads (`torn`: reader ages from mtime)",
    "sched.requeue": "runtime/queue.py expired-lease takeover",
    "fleet.write":
        "serve/fleet.py daemon beat payloads (`torn`: mtime ageing)",
    "fleet.supervisor":
        "serve/supervisor.py decision round (`kill`: supervisor dies "
        "mid-burst, successor re-adopts from beats)",
}

KNOWN_SITES = frozenset(SITE_DOCS)


def sites_markdown_table() -> str:
    """The README fault-site table, generated so prose cannot drift from
    the registry (asserted byte-identical by tests/test_ctt_proto.py)."""
    lines = ["| site | where it fires |", "| --- | --- |"]
    for site in sorted(SITE_DOCS):
        lines.append(f"| `{site}` | {SITE_DOCS[site]} |")
    return "\n".join(lines)

KNOWN_ACTIONS = frozenset({"io_error", "fail", "kill", "stall", "torn"})


class _Entry:
    """One parsed spec entry plus its runtime state (RNG stream, counters)."""

    __slots__ = (
        "site", "action", "p", "ids", "times", "after", "stall_s",
        "keep_bytes", "index", "rng", "seen", "fired",
    )

    def __init__(self, site: str, action: str, index: int):
        self.site = site
        self.action = action
        self.index = index
        self.p: Optional[float] = None
        self.ids: Optional[frozenset] = None
        self.times: Optional[int] = None
        self.after = 0
        self.stall_s = 5.0
        self.keep_bytes: Optional[int] = None
        self.rng: Optional[random.Random] = None
        self.seen = 0
        self.fired = 0

    def describe(self) -> str:
        return f"{self.site}:{self.action}#{self.index}"


def _parse_entry(raw: str, index: int) -> _Entry:
    segs = raw.split(":")
    if len(segs) < 2 or len(segs) > 3:
        raise FaultSpecError(
            f"fault entry {raw!r} is not site:action[:params]"
        )
    site, action = segs[0].strip(), segs[1].strip()
    if site not in KNOWN_SITES:
        raise FaultSpecError(
            f"unknown fault site {site!r} (known: {sorted(KNOWN_SITES)})"
        )
    if action not in KNOWN_ACTIONS:
        raise FaultSpecError(
            f"unknown fault action {action!r} (known: {sorted(KNOWN_ACTIONS)})"
        )
    if action == "torn" and not site.endswith(".write"):
        raise FaultSpecError(
            f"action 'torn' only applies to write sites, not {site!r}"
        )
    entry = _Entry(site, action, index)
    if len(segs) == 3:
        for param in segs[2].split(","):
            param = param.strip()
            if not param:
                continue
            try:
                if param == "once":
                    entry.times = 1
                elif param.startswith("p="):
                    entry.p = float(param[2:])
                    if not 0.0 <= entry.p <= 1.0:
                        raise ValueError
                elif param.startswith("ids="):
                    entry.ids = frozenset(
                        int(t) for t in param[4:].split("|") if t
                    )
                elif param.startswith("times="):
                    entry.times = int(param[6:])
                elif param.startswith("after="):
                    entry.after = int(param[6:])
                elif param.startswith("s="):
                    entry.stall_s = float(param[2:])
                elif param.startswith("bytes="):
                    entry.keep_bytes = int(param[6:])
                else:
                    raise ValueError
            except ValueError:
                raise FaultSpecError(
                    f"bad fault param {param!r} in entry {raw!r}"
                ) from None
    return entry


def parse_spec(spec: str) -> Tuple[List[_Entry], int]:
    """``(entries, seed)`` for a spec string; raises FaultSpecError."""
    entries: List[_Entry] = []
    seed = 0
    index = 0
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        if raw.startswith("seed="):
            try:
                seed = int(raw[5:])
            except ValueError:
                raise FaultSpecError(f"bad seed in {raw!r}") from None
            continue
        entries.append(_parse_entry(raw, index))
        index += 1
    if not entries:
        raise FaultSpecError(
            f"{ENV_SPEC} is set but contains no fault entries: {spec!r}"
        )
    return entries, seed


class _Plan:
    """Parsed spec + per-entry state.  One instance per process; ``check``
    is locked so concurrent block threads keep counters coherent (thread
    interleavings are inherently non-deterministic anyway — determinism
    holds for deterministic call sequences)."""

    def __init__(self, entries: List[_Entry], seed: int,
                 state_dir: Optional[str]):
        self.seed = seed
        self.state_dir = state_dir
        self.entries = entries
        self.by_site: Dict[str, List[_Entry]] = {}
        self.log: List[Tuple[str, str, int]] = []  # (site, action, seen#)
        self.lock = threading.Lock()
        for e in entries:
            # per-entry stream: decisions of one entry never shift another's
            stream_id = zlib.crc32(f"{e.site}#{e.index}".encode())
            e.rng = random.Random((seed << 32) ^ stream_id)
            self.by_site.setdefault(e.site, []).append(e)

    # -- cross-process once/times latch -----------------------------------

    def _claim(self, e: _Entry) -> bool:
        """True if this firing slot is ours.  With a state dir, slots are
        O_CREAT|O_EXCL latch files shared by every process reading the same
        spec; without, a process-local counter."""
        if e.times is None:
            return True
        if self.state_dir is None:
            if e.fired >= e.times:
                return False
            return True
        os.makedirs(self.state_dir, exist_ok=True)
        for slot in range(e.times):
            path = os.path.join(
                self.state_dir, f"{e.site}.{e.index}.fired{slot}"
            )
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                continue
            os.write(fd, f"pid={os.getpid()}\n".encode())
            os.close(fd)
            return True
        return False

    # -- matching ----------------------------------------------------------

    def _matches(self, e: _Entry, ctx: Dict[str, Any]) -> bool:
        """Advances ``seen`` and the RNG stream; claims a latch slot last so
        an exhausted entry still keeps its stream deterministic."""
        if e.ids is not None and ctx.get("id") not in e.ids:
            return False
        e.seen += 1
        if e.seen <= e.after:
            return False
        if e.p is not None and e.rng.random() >= e.p:
            return False
        if not self._claim(e):
            return False
        e.fired += 1
        return True

    def _note(self, e: _Entry, ctx: Dict[str, Any]) -> None:
        self.log.append((e.site, e.action, e.seen))
        try:
            from ..obs import metrics as obs_metrics
            from ..obs import trace as obs_trace

            obs_metrics.inc("faults.injected")
            obs_metrics.inc(f"faults.injected.{e.site}")
            obs_trace.event(
                f"fault:{e.site}", "fault", 0.0,
                action=e.action, entry=e.index, seen=e.seen,
                **{k: v for k, v in ctx.items() if isinstance(v, (int, str))},
            )
        except Exception:  # ctt: noqa[CTT009] telemetry about an injected fault must never mask the fault itself
            pass  # pragma: no cover

    # -- public ------------------------------------------------------------

    def check(self, site: str, ctx: Dict[str, Any]) -> None:
        entries = self.by_site.get(site)
        if not entries:
            return
        fired: Optional[_Entry] = None
        with self.lock:
            for e in entries:
                if e.action == "torn":
                    continue  # torn fires through mangle() only
                if self._matches(e, ctx):
                    fired = e
                    self._note(e, ctx)
                    break
        if fired is None:
            return
        if fired.action == "kill":
            os._exit(KILL_EXIT_CODE)
        if fired.action == "stall":
            time.sleep(fired.stall_s)
            return
        if fired.action == "io_error":
            raise OSError(
                errno.EIO, f"injected io_error at {site} ({fired.describe()})"
            )
        raise FaultInjected(
            f"injected failure at {site} ({fired.describe()})"
        )

    def mangle(self, site: str, payload: bytes,
               ctx: Dict[str, Any]) -> Optional[bytes]:
        entries = self.by_site.get(site)
        if not entries:
            return None
        with self.lock:
            for e in entries:
                if e.action != "torn":
                    continue
                if self._matches(e, ctx):
                    self._note(e, ctx)
                    keep = (
                        e.keep_bytes if e.keep_bytes is not None
                        else max(1, len(payload) // 2)
                    )
                    return payload[:keep]
        return None


_PLAN: Optional[_Plan] = None


def configure(spec: Optional[str] = None, seed: Optional[int] = None,
              state_dir: Optional[str] = None) -> bool:
    """(Re)build the process fault plan.  With no arguments, re-reads
    ``CTT_FAULTS`` / ``CTT_FAULT_STATE_DIR`` — unset/empty disables.
    Returns True when a plan is armed."""
    global _PLAN
    if spec is None:
        spec = os.environ.get(ENV_SPEC)
    if not spec:
        _PLAN = None
        return False
    entries, spec_seed = parse_spec(spec)
    if seed is not None:
        spec_seed = seed
    if state_dir is None:
        state_dir = os.environ.get(ENV_STATE) or None
    _PLAN = _Plan(entries, spec_seed, state_dir)
    return True


def reset() -> None:
    """Disarm the harness (test isolation helper)."""
    global _PLAN
    _PLAN = None


def enabled() -> bool:
    return _PLAN is not None


def check(site: str, **ctx: Any) -> None:
    """Injection site: no-op unless a plan is armed and an entry fires.
    May raise OSError/FaultInjected, sleep (stall), or hard-exit (kill)."""
    plan = _PLAN
    if plan is None:
        return
    plan.check(site, ctx)


def mangle(site: str, payload: bytes, **ctx: Any) -> Optional[bytes]:
    """Torn-write site: returns the truncated payload when a ``torn`` entry
    fires, else None (caller writes the original)."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.mangle(site, payload, ctx)


def decision_log() -> List[Tuple[str, str, int]]:
    """Fired faults so far: ``(site, action, matching-check ordinal)`` —
    the sequence the determinism test compares across processes."""
    plan = _PLAN
    return list(plan.log) if plan is not None else []


configure()
