"""cluster_tools_tpu — a TPU-native framework for distributed 3D bio-image segmentation.

A ground-up rebuild of the capabilities of `cluster_tools`
(constantinpape/cluster_tools, mirrored as tranorrepository/cluster_tools): resumable,
block-decomposed workflows over chunked zarr/n5/hdf5 volumes — distance-transform
watersheds, distributed connected components, mutex watershed, region-adjacency-graph
extraction, edge-feature accumulation, (lifted) multicut, stitching, relabeling,
evaluation, multiscale export and NN inference.

Architecture (TPU-first, not a port):
  * the per-block hot path is a single jit-compiled JAX/XLA program (optionally Pallas),
    batched over blocks and sharded across a `jax.sharding.Mesh` with `shard_map`;
  * halo exchange and label merges ride ICI collectives instead of the reference's
    shared-filesystem data plane (reference: SURVEY.md §2.9);
  * the resumable task DAG / JSON-config / chunked-IO architecture of the reference is
    kept as the host-side control plane (reference: cluster_tools/cluster_tasks.py).
"""

__version__ = "0.1.0"

from .runtime.task import BlockTask, Task, FailedBlocksError
from .runtime.workflow import WorkflowBase, build
from .runtime import config as config

__all__ = [
    "BlockTask",
    "Task",
    "FailedBlocksError",
    "WorkflowBase",
    "build",
    "config",
]
