"""ctypes bindings for the native C++ solver library.

Builds ``libctt_solvers.so`` from ``solvers.cpp`` with g++ on first use (no
pybind11 in this environment; plain C ABI + ctypes instead).  ``available()``
reports whether the native library could be built/loaded; callers fall back to
the pure-python implementations in ``ops.multicut`` / ``ops.mws``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "solvers.cpp")
_LIB = os.path.join(_HERE, "libctt_solvers.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", _LIB, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError, subprocess.TimeoutExpired) as e:
        stderr = getattr(e, "stderr", b"")
        print(f"[native] build failed ({e}); falling back to python solvers\n"
              f"{stderr.decode() if isinstance(stderr, bytes) else stderr}")
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            # a prebuilt .so from another toolchain (GLIBCXX/arch mismatch)
            # must trigger a local rebuild, not crash every caller
            print(f"[native] prebuilt library unusable ({e}); rebuilding")
            if not _build():
                _build_failed = True
                return None
            try:
                lib = ctypes.CDLL(_LIB)
            except OSError as e2:
                print(f"[native] rebuilt library failed to load ({e2}); "
                      "falling back to python solvers")
                _build_failed = True
                return None
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.gaec_multicut.argtypes = [
            ctypes.c_int64, ctypes.c_int64, i64p, f64p, i64p,
        ]
        lib.agglomerative_clustering.argtypes = [
            ctypes.c_int64, ctypes.c_int64, i64p, f64p, ctypes.c_void_p,
            ctypes.c_double, i64p,
        ]
        lib.mutex_watershed.argtypes = [
            ctypes.c_int64, ctypes.c_int64, i64p, f64p, u8p, i64p,
        ]
        lib.lifted_gaec.argtypes = [
            ctypes.c_int64, ctypes.c_int64, i64p, f64p,
            ctypes.c_int64, i64p, f64p, i64p,
        ]
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.dt_watershed_cpu.argtypes = [
            f32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_int64, i32p,
        ]
        lib.dt_watershed_cpu.restype = ctypes.c_int64
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def gaec_multicut(n_nodes: int, uv: np.ndarray, costs: np.ndarray) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native solver library unavailable")
    uv = np.ascontiguousarray(uv, dtype=np.int64)
    costs = np.ascontiguousarray(costs, dtype=np.float64)
    labels = np.empty(n_nodes, dtype=np.int64)
    lib.gaec_multicut(n_nodes, uv.shape[0], uv.reshape(-1), costs, labels)
    return labels


def agglomerative_clustering(
    n_nodes: int,
    uv: np.ndarray,
    weights: np.ndarray,
    threshold: float,
    sizes: Optional[np.ndarray] = None,
) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native solver library unavailable")
    uv = np.ascontiguousarray(uv, dtype=np.int64)
    weights = np.ascontiguousarray(weights, dtype=np.float64)
    labels = np.empty(n_nodes, dtype=np.int64)
    if sizes is None:
        sizes_ptr = None
    else:
        sizes = np.ascontiguousarray(sizes, dtype=np.float64)
        sizes_ptr = sizes.ctypes.data_as(ctypes.c_void_p)
    lib.agglomerative_clustering(
        n_nodes, uv.shape[0], uv.reshape(-1), weights, sizes_ptr,
        float(threshold), labels,
    )
    return labels


def lifted_gaec(
    n_nodes: int,
    uv: np.ndarray,
    costs: np.ndarray,
    lifted_uv: np.ndarray,
    lifted_costs: np.ndarray,
) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native solver library unavailable")
    uv = np.ascontiguousarray(uv, dtype=np.int64)
    costs = np.ascontiguousarray(costs, dtype=np.float64)
    lifted_uv = np.ascontiguousarray(lifted_uv, dtype=np.int64)
    lifted_costs = np.ascontiguousarray(lifted_costs, dtype=np.float64)
    labels = np.empty(n_nodes, dtype=np.int64)
    lib.lifted_gaec(
        n_nodes, uv.shape[0], uv.reshape(-1), costs,
        lifted_uv.shape[0], lifted_uv.reshape(-1), lifted_costs, labels,
    )
    return labels


def mutex_watershed(
    n_nodes: int, uv: np.ndarray, weights: np.ndarray, attractive: np.ndarray
) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native solver library unavailable")
    uv = np.ascontiguousarray(uv, dtype=np.int64)
    weights = np.ascontiguousarray(weights, dtype=np.float64)
    attractive = np.ascontiguousarray(attractive, dtype=np.uint8)
    labels = np.empty(n_nodes, dtype=np.int64)
    lib.mutex_watershed(
        n_nodes, uv.shape[0], uv.reshape(-1), weights, attractive, labels
    )
    return labels


def dt_watershed_cpu(
    input_: np.ndarray,
    threshold: float = 0.25,
    sigma_seeds: float = 2.0,
    sigma_weights: float = 2.0,
    alpha: float = 0.8,
    size_filter: int = 25,
) -> "tuple[np.ndarray, int]":
    """Single-core C++ DT-watershed (per-slice 2d mode) — the honest host
    benchmark baseline for ops.watershed.dt_watershed (vigra moral
    equivalent, reference watershed/watershed.py:286-344)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native solver library unavailable")
    x = np.ascontiguousarray(input_, dtype=np.float32)
    if x.ndim != 3:
        raise ValueError("expected a 3d (z, y, x) volume")
    labels = np.zeros(x.shape, dtype=np.int32)
    n_seeds = lib.dt_watershed_cpu(
        x.reshape(-1), x.shape[0], x.shape[1], x.shape[2],
        float(threshold), float(sigma_seeds), float(sigma_weights),
        float(alpha), int(size_filter), labels.reshape(-1),
    )
    return labels, int(n_seeds)
