"""Prebuild the native solver library: ``python -m cluster_tools_tpu.native.build``."""

from . import _build, available

if __name__ == "__main__":
    ok = available()
    print("native solvers:", "OK" if ok else "BUILD FAILED (python fallbacks active)")
    raise SystemExit(0 if ok else 1)
