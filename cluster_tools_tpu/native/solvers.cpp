// Native combinatorial graph solvers.
//
// The TPU framework keeps inherently sequential, pointer-chasing graph
// algorithms on the host in C++ (the role nifty/affogato play for the
// reference — SURVEY.md §2.10): greedy additive edge contraction (GAEC)
// multicut, threshold agglomerative clustering, and the mutex watershed.
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
//
// Reference behaviors mirrored:
//   * GAEC: elf.segmentation.multicut 'greedy-additive' solver
//     (multicut/solve_subproblems.py:184, solve_global.py:147-153)
//   * agglomerative clustering: elf mala_clustering / agglomerative_clustering
//     (watershed/agglomerate.py:190-198, agglomerative_clustering.py:138)
//   * mutex watershed: affogato compute_mws_segmentation
//     (mutex_watershed/mws_blocks.py:11)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <queue>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct UnionFind {
    std::vector<int64_t> parent;
    std::vector<int64_t> rank_;

    explicit UnionFind(int64_t n) : parent(n), rank_(n, 0) {
        for (int64_t i = 0; i < n; ++i) parent[i] = i;
    }

    int64_t find(int64_t x) {
        while (parent[x] != x) {
            parent[x] = parent[parent[x]];
            x = parent[x];
        }
        return x;
    }

    // returns the new root (or -1 if already merged)
    int64_t merge(int64_t a, int64_t b) {
        a = find(a);
        b = find(b);
        if (a == b) return -1;
        if (rank_[a] < rank_[b]) std::swap(a, b);
        parent[b] = a;
        if (rank_[a] == rank_[b]) ++rank_[a];
        return a;
    }
};

struct HeapEntry {
    double priority;
    int64_t u, v;
    uint64_t stamp;  // lazy invalidation: entry valid iff stamp matches edge stamp

    bool operator<(const HeapEntry& o) const { return priority < o.priority; }
};

struct EdgeVal {
    double w;  // accumulated value: sum (additive) or weighted mean (mean mode)
    double c;  // accumulated multiplicity (edge count / size)
};

// Dynamic contracted graph: per-root adjacency map root -> (neighbor -> EdgeVal).
struct DynamicGraph {
    std::vector<std::unordered_map<int64_t, EdgeVal>> adj;
    std::unordered_map<uint64_t, uint64_t> edge_stamp;  // key(u,v) -> stamp
    uint64_t stamp_counter = 0;

    explicit DynamicGraph(int64_t n) : adj(n) {}

    static uint64_t key(int64_t u, int64_t v, int64_t n) {
        if (u > v) std::swap(u, v);
        return static_cast<uint64_t>(u) * static_cast<uint64_t>(n) +
               static_cast<uint64_t>(v);
    }
};

// Core greedy agglomeration: repeatedly contract the max-priority edge while
// priority > stop_priority.  Parallel edges accumulate additively
// (mean_mode=false, GAEC) or by count-weighted mean (mean_mode=true,
// mala-style clustering; priority = -mean so the *lowest* boundary merges
// first).  Returns node -> root labels in `labels`.
void greedy_agglomeration(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                          const double* weights, const double* counts,
                          bool mean_mode, double stop_priority,
                          int64_t* labels) {
    UnionFind uf(n_nodes);
    DynamicGraph g(n_nodes);
    std::priority_queue<HeapEntry> heap;

    auto combine = [mean_mode](const EdgeVal& a, const EdgeVal& b) {
        if (mean_mode)
            return EdgeVal{(a.w * a.c + b.w * b.c) / (a.c + b.c), a.c + b.c};
        return EdgeVal{a.w + b.w, a.c + b.c};
    };
    auto priority = [mean_mode](const EdgeVal& e) {
        return mean_mode ? -e.w : e.w;
    };

    for (int64_t e = 0; e < n_edges; ++e) {
        int64_t u = uv[2 * e], v = uv[2 * e + 1];
        if (u == v) continue;
        EdgeVal val{weights[e], counts ? counts[e] : 1.0};
        auto it = g.adj[u].find(v);
        if (it == g.adj[u].end()) {
            g.adj[u][v] = val;
            g.adj[v][u] = val;
        } else {
            EdgeVal merged = combine(it->second, val);
            it->second = merged;
            g.adj[v][u] = merged;
        }
    }
    for (int64_t u = 0; u < n_nodes; ++u) {
        for (const auto& kv : g.adj[u]) {
            if (kv.first > u) {
                uint64_t k = DynamicGraph::key(u, kv.first, n_nodes);
                g.edge_stamp[k] = 0;
                heap.push({priority(kv.second), u, kv.first, 0});
            }
        }
    }

    while (!heap.empty()) {
        HeapEntry top = heap.top();
        heap.pop();
        int64_t u = uf.find(top.u), v = uf.find(top.v);
        if (u == v) continue;
        uint64_t k = DynamicGraph::key(u, v, n_nodes);
        auto st = g.edge_stamp.find(k);
        if (st == g.edge_stamp.end() || st->second != top.stamp) continue;
        if (top.priority <= stop_priority) break;

        // contract v into u (keep the larger adjacency as the base)
        if (g.adj[u].size() < g.adj[v].size()) std::swap(u, v);
        int64_t root = uf.merge(u, v);
        if (root != u) {  // union-by-rank picked v's tree; relabel so data at u
            std::swap(u, v);
        }
        // move v's edges into u
        g.adj[u].erase(v);
        g.adj[v].erase(u);
        for (const auto& kv : g.adj[v]) {
            int64_t w = kv.first;
            g.adj[w].erase(v);
            auto it = g.adj[u].find(w);
            EdgeVal merged;
            if (it == g.adj[u].end()) {
                merged = kv.second;
                g.adj[u][w] = merged;
                g.adj[w][u] = merged;
            } else {
                merged = combine(it->second, kv.second);
                it->second = merged;
                g.adj[w][u] = merged;
            }
            uint64_t nk = DynamicGraph::key(u, w, n_nodes);
            uint64_t stamp = ++g.stamp_counter;
            g.edge_stamp[nk] = stamp;
            heap.push({priority(merged), u, w, stamp});
        }
        g.adj[v].clear();
    }

    for (int64_t i = 0; i < n_nodes; ++i) labels[i] = uf.find(i);
}

// Lifted GAEC: contraction only along local edges, priority = combined
// local+lifted inter-cluster cost, both cost maps merge on contraction
// (nifty's liftedGraphEdgeWeightedClusterPolicy behavior, used by the
// reference through elf's lifted 'greedy-additive' solver).
void lifted_gaec_impl(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                      const double* costs, int64_t n_lifted,
                      const int64_t* lifted_uv, const double* lifted_costs,
                      int64_t* labels) {
    UnionFind uf(n_nodes);
    std::vector<std::unordered_map<int64_t, double>> local(n_nodes);
    std::vector<std::unordered_map<int64_t, double>> lifted(n_nodes);
    std::unordered_map<uint64_t, uint64_t> edge_stamp;
    uint64_t stamp_counter = 0;
    std::priority_queue<HeapEntry> heap;

    for (int64_t e = 0; e < n_edges; ++e) {
        int64_t u = uv[2 * e], v = uv[2 * e + 1];
        if (u == v) continue;
        local[u][v] += costs[e];
        local[v][u] = local[u][v];
    }
    for (int64_t e = 0; e < n_lifted; ++e) {
        int64_t u = lifted_uv[2 * e], v = lifted_uv[2 * e + 1];
        if (u == v) continue;
        lifted[u][v] += lifted_costs[e];
        lifted[v][u] = lifted[u][v];
    }
    auto combined = [&](int64_t u, int64_t v) {
        double c = local[u].at(v);
        auto it = lifted[u].find(v);
        if (it != lifted[u].end()) c += it->second;
        return c;
    };
    for (int64_t u = 0; u < n_nodes; ++u) {
        for (const auto& kv : local[u]) {
            if (kv.first > u) {
                edge_stamp[DynamicGraph::key(u, kv.first, n_nodes)] = 0;
                heap.push({combined(u, kv.first), u, kv.first, 0});
            }
        }
    }

    while (!heap.empty()) {
        HeapEntry top = heap.top();
        heap.pop();
        int64_t u = uf.find(top.u), v = uf.find(top.v);
        if (u == v) continue;
        uint64_t k = DynamicGraph::key(u, v, n_nodes);
        auto st = edge_stamp.find(k);
        if (st == edge_stamp.end() || st->second != top.stamp) continue;
        if (top.priority <= 0.0) break;

        if (local[u].size() + lifted[u].size() <
            local[v].size() + lifted[v].size())
            std::swap(u, v);
        int64_t root = uf.merge(u, v);
        if (root != u) std::swap(u, v);
        local[u].erase(v);
        local[v].erase(u);
        lifted[u].erase(v);
        lifted[v].erase(u);
        std::unordered_set<int64_t> touched;
        for (auto* m : {&local, &lifted}) {
            for (const auto& kv : (*m)[v]) {
                int64_t w = kv.first;
                (*m)[w].erase(v);
                (*m)[u][w] += kv.second;
                (*m)[w][u] = (*m)[u][w];
                touched.insert(w);
            }
            (*m)[v].clear();
        }
        for (const auto& kv : local[u]) touched.insert(kv.first);
        for (int64_t w : touched) {
            if (local[u].find(w) == local[u].end()) continue;  // lifted-only
            uint64_t nk = DynamicGraph::key(u, w, n_nodes);
            uint64_t stamp = ++stamp_counter;
            edge_stamp[nk] = stamp;
            heap.push({combined(u, w), u, w, stamp});
        }
    }

    for (int64_t i = 0; i < n_nodes; ++i) labels[i] = uf.find(i);
}

// ---------------------------------------------------------------------------
// Single-core DT-watershed benchmark baseline.
//
// The honest host comparator for the fused TPU program (ops/watershed.py
// dt_watershed): the same per-block pipeline the reference runs through
// vigra/C++ (watershed/watershed.py:286-344) — threshold → per-slice exact
// 2d EDT (Felzenszwalb) → gaussian → 3x3 maxima → CC seeds → height map →
// priority flood → size filter — implemented as plain single-thread C++.
// ---------------------------------------------------------------------------

// exact 1d squared distance transform (Felzenszwalb & Huttenlocher lower
// envelope), f = input costs, d = output, v/z = scratch (size n / n+1)
void edt_1d(const float* f, float* d, int64_t n, int64_t* v, float* z) {
    int64_t k = 0;
    v[0] = 0;
    z[0] = -3.0e38f;
    z[1] = 3.0e38f;
    for (int64_t q = 1; q < n; ++q) {
        float s;
        while (true) {
            int64_t p = v[k];
            s = ((f[q] + q * q) - (f[p] + p * p)) / (2.0f * (q - p));
            if (s > z[k]) break;
            --k;
        }
        ++k;
        v[k] = q;
        z[k] = s;
        z[k + 1] = 3.0e38f;
    }
    k = 0;
    for (int64_t q = 0; q < n; ++q) {
        while (z[k + 1] < q) ++k;
        int64_t p = v[k];
        d[q] = (q - p) * (q - p) + f[p];
    }
}

// separable 2d squared EDT of one slice (distance to nearest background==0)
void edt_2d(const uint8_t* fg, float* dist, int64_t ny, int64_t nx,
            float* tmp, float* col, float* cold, int64_t* v, float* z) {
    const float BIG = 1.0e10f;
    for (int64_t y = 0; y < ny; ++y) {
        // exact 1d line distance along x, squared
        float run = BIG;
        for (int64_t x = 0; x < nx; ++x) {
            run = fg[y * nx + x] ? ((run >= BIG) ? BIG : run + 1.0f) : 0.0f;
            tmp[y * nx + x] = run;
        }
        run = BIG;
        for (int64_t x = nx - 1; x >= 0; --x) {
            run = fg[y * nx + x] ? ((run >= BIG) ? BIG : run + 1.0f) : 0.0f;
            float m = std::min(tmp[y * nx + x], run);
            tmp[y * nx + x] = (m >= BIG) ? BIG : m * m;
        }
    }
    for (int64_t x = 0; x < nx; ++x) {
        for (int64_t y = 0; y < ny; ++y) col[y] = tmp[y * nx + x];
        edt_1d(col, cold, ny, v, z);
        for (int64_t y = 0; y < ny; ++y) dist[y * nx + x] = cold[y];
    }
}

// separable gaussian blur of one slice, reflect boundary
void gaussian_2d(const float* in, float* out, int64_t ny, int64_t nx,
                 float sigma, float* tmp) {
    if (sigma <= 0.0f) {
        std::memcpy(out, in, sizeof(float) * ny * nx);
        return;
    }
    int64_t radius = static_cast<int64_t>(4.0f * sigma + 0.5f);
    std::vector<float> kern(2 * radius + 1);
    float s2 = 2.0f * sigma * sigma, sum = 0.0f;
    for (int64_t i = -radius; i <= radius; ++i) {
        kern[i + radius] = std::exp(-(float)(i * i) / s2);
        sum += kern[i + radius];
    }
    for (auto& k : kern) k /= sum;
    auto reflect = [](int64_t i, int64_t n) {
        // scipy 'reflect' mode: (d c b a | a b c d | d c b a)
        while (i < 0 || i >= n) {
            if (i < 0) i = -i - 1;
            if (i >= n) i = 2 * n - i - 1;
        }
        return i;
    };
    for (int64_t y = 0; y < ny; ++y)
        for (int64_t x = 0; x < nx; ++x) {
            float acc = 0.0f;
            for (int64_t k = -radius; k <= radius; ++k)
                acc += kern[k + radius] * in[y * nx + reflect(x + k, nx)];
            tmp[y * nx + x] = acc;
        }
    for (int64_t y = 0; y < ny; ++y)
        for (int64_t x = 0; x < nx; ++x) {
            float acc = 0.0f;
            for (int64_t k = -radius; k <= radius; ++k)
                acc += kern[k + radius] * tmp[reflect(y + k, ny) * nx + x];
            out[y * nx + x] = acc;
        }
}

struct FloodEntry {
    float h;
    uint64_t order;
    int64_t idx;
    bool operator>(const FloodEntry& o) const {
        return h != o.h ? h > o.h : order > o.order;
    }
};

// seeded priority-flood of one slice, 4-connectivity (vigra watershedsNew
// moral equivalent: lowest height first, FIFO within plateaus)
void flood_2d(const float* hmap, const uint8_t* mask, int32_t* labels,
              int64_t ny, int64_t nx) {
    std::priority_queue<FloodEntry, std::vector<FloodEntry>,
                        std::greater<FloodEntry>> heap;
    uint64_t order = 0;
    std::vector<uint8_t> visited(ny * nx, 0);
    for (int64_t i = 0; i < ny * nx; ++i)
        if (labels[i] > 0) {
            visited[i] = 1;
            heap.push({hmap[i], order++, i});
        }
    const int64_t dy[4] = {-1, 1, 0, 0}, dx[4] = {0, 0, -1, 1};
    while (!heap.empty()) {
        FloodEntry e = heap.top();
        heap.pop();
        int64_t y = e.idx / nx, x = e.idx % nx;
        int32_t lab = labels[e.idx];
        for (int64_t d = 0; d < 4; ++d) {
            int64_t yy = y + dy[d], xx = x + dx[d];
            if (yy < 0 || yy >= ny || xx < 0 || xx >= nx) continue;
            int64_t j = yy * nx + xx;
            if (visited[j] || !mask[j]) continue;
            visited[j] = 1;
            labels[j] = lab;
            heap.push({hmap[j], order++, j});
        }
    }
}

}  // namespace

extern "C" {

// Full per-block DT-watershed, single core, per-slice (2d) mode — the
// benchmark baseline for the fused TPU program.  input: (nz, ny, nx) f32,
// labels out: int32 (globally unique across slices).  Returns n_seeds.
int64_t dt_watershed_cpu(const float* input, int64_t nz, int64_t ny,
                         int64_t nx, float threshold, float sigma_seeds,
                         float sigma_weights, float alpha, int64_t size_filter,
                         int32_t* labels) {
    const int64_t sz = ny * nx;
    std::vector<uint8_t> fg(sz);
    std::vector<float> dist(sz), smooth(sz), hmap(sz), tmp(sz);
    std::vector<float> col(ny), cold(ny), z(ny + 1);
    std::vector<int64_t> v(ny);
    int32_t next_label = 1;
    std::vector<int64_t> stack;

    for (int64_t zi = 0; zi < nz; ++zi) {
        const float* x = input + zi * sz;
        int32_t* lab = labels + zi * sz;
        for (int64_t i = 0; i < sz; ++i) fg[i] = x[i] < threshold;
        edt_2d(fg.data(), dist.data(), ny, nx, tmp.data(), col.data(),
               cold.data(), v.data(), z.data());
        float dmax = 0.0f;
        for (int64_t i = 0; i < sz; ++i) {
            dist[i] = std::sqrt(dist[i]);
            dmax = std::max(dmax, dist[i]);
        }
        gaussian_2d(dist.data(), smooth.data(), ny, nx, sigma_seeds,
                    tmp.data());
        // seeds: 3x3 local maxima of smoothed dt (dt>0), 8-conn CC label
        std::memset(lab, 0, sizeof(int32_t) * sz);
        std::vector<uint8_t> maxima(sz, 0);
        for (int64_t y = 0; y < ny; ++y)
            for (int64_t xx = 0; xx < nx; ++xx) {
                int64_t i = y * nx + xx;
                if (dist[i] <= 0.0f) continue;
                float c = smooth[i];
                bool is_max = true;
                for (int64_t ddy = -1; ddy <= 1 && is_max; ++ddy)
                    for (int64_t ddx = -1; ddx <= 1; ++ddx) {
                        int64_t yy = y + ddy, xc = xx + ddx;
                        if (yy < 0 || yy >= ny || xc < 0 || xc >= nx) continue;
                        if (smooth[yy * nx + xc] > c) {
                            is_max = false;
                            break;
                        }
                    }
                maxima[i] = is_max;
            }
        for (int64_t i = 0; i < sz; ++i) {
            if (!maxima[i] || lab[i] != 0) continue;
            int32_t id = next_label++;
            stack.clear();
            stack.push_back(i);
            lab[i] = id;
            while (!stack.empty()) {
                int64_t j = stack.back();
                stack.pop_back();
                int64_t y = j / nx, xx = j % nx;
                for (int64_t ddy = -1; ddy <= 1; ++ddy)
                    for (int64_t ddx = -1; ddx <= 1; ++ddx) {
                        int64_t yy = y + ddy, xc = xx + ddx;
                        if (yy < 0 || yy >= ny || xc < 0 || xc >= nx) continue;
                        int64_t k = yy * nx + xc;
                        if (maxima[k] && lab[k] == 0) {
                            lab[k] = id;
                            stack.push_back(k);
                        }
                    }
            }
        }
        // height map alpha*x + (1-alpha)*(1 - dt/dmax), smoothed
        float inv = dmax > 1e-6f ? 1.0f / dmax : 0.0f;
        for (int64_t i = 0; i < sz; ++i)
            tmp[i] = alpha * x[i] + (1.0f - alpha) * (1.0f - dist[i] * inv);
        gaussian_2d(tmp.data(), hmap.data(), ny, nx, sigma_weights,
                    smooth.data());
        flood_2d(hmap.data(), fg.data(), lab, ny, nx);
    }
    int64_t n_seeds = next_label - 1;

    if (size_filter > 0) {
        std::vector<int64_t> counts(next_label, 0);
        const int64_t total = nz * sz;
        for (int64_t i = 0; i < total; ++i) ++counts[labels[i]];
        std::vector<uint8_t> drop(next_label, 0);
        for (int64_t l = 1; l < next_label; ++l)
            drop[l] = counts[l] < size_filter;
        for (int64_t zi = 0; zi < nz; ++zi) {
            const float* x = input + zi * sz;
            int32_t* lab = labels + zi * sz;
            bool any = false;
            for (int64_t i = 0; i < sz; ++i) {
                fg[i] = x[i] < threshold;
                if (lab[i] > 0 && drop[lab[i]]) {
                    lab[i] = 0;
                    any = true;
                }
            }
            if (!any) continue;
            // re-flood freed voxels from the surviving labels
            edt_2d(fg.data(), dist.data(), ny, nx, tmp.data(), col.data(),
                   cold.data(), v.data(), z.data());
            float dmax = 0.0f;
            for (int64_t i = 0; i < sz; ++i) {
                dist[i] = std::sqrt(dist[i]);
                dmax = std::max(dmax, dist[i]);
            }
            float inv = dmax > 1e-6f ? 1.0f / dmax : 0.0f;
            for (int64_t i = 0; i < sz; ++i)
                tmp[i] = alpha * x[i] + (1.0f - alpha) * (1.0f - dist[i] * inv);
            gaussian_2d(tmp.data(), hmap.data(), ny, nx, sigma_weights,
                        smooth.data());
            flood_2d(hmap.data(), fg.data(), lab, ny, nx);
        }
    }
    return n_seeds;
}

// Lifted multicut via lifted-GAEC (see lifted_gaec_impl).
void lifted_gaec(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                 const double* costs, int64_t n_lifted,
                 const int64_t* lifted_uv, const double* lifted_costs,
                 int64_t* labels) {
    lifted_gaec_impl(n_nodes, n_edges, uv, costs, n_lifted, lifted_uv,
                     lifted_costs, labels);
}

// GAEC multicut: contract while the best merge has positive cost.
// labels receives the root id per node (not consecutive).
void gaec_multicut(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                   const double* costs, int64_t* labels) {
    greedy_agglomeration(n_nodes, n_edges, uv, costs, nullptr,
                         /*mean_mode=*/false, 0.0, labels);
}

// Threshold agglomeration on edge weights where LOW weight = merge first and
// parallel edges combine by size-weighted mean (mala semantics: weights are
// boundary probabilities).  Merges until the cheapest remaining mean boundary
// exceeds `threshold`.  `sizes` may be null (unit sizes).
void agglomerative_clustering(int64_t n_nodes, int64_t n_edges,
                              const int64_t* uv, const double* weights,
                              const double* sizes, double threshold,
                              int64_t* labels) {
    greedy_agglomeration(n_nodes, n_edges, uv, weights, sizes,
                         /*mean_mode=*/true, -threshold, labels);
}

// Mutex watershed on a weighted graph: edges sorted by |weight| descending are
// processed Kruskal-style; attractive edges (attractive[e] != 0) merge unless a
// mutex exists, repulsive edges install mutexes between clusters.
// (affogato's graph MWS algorithm.)
void mutex_watershed(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                     const double* weights, const uint8_t* attractive,
                     int64_t* labels) {
    std::vector<int64_t> order(n_edges);
    for (int64_t i = 0; i < n_edges; ++i) order[i] = i;
    std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
        return weights[a] > weights[b];
    });

    UnionFind uf(n_nodes);
    // per-root mutex partner sets
    std::vector<std::unordered_set<int64_t>> mutexes(n_nodes);

    auto have_mutex = [&](int64_t ra, int64_t rb) {
        const auto& small = mutexes[ra].size() < mutexes[rb].size() ? mutexes[ra]
                                                                    : mutexes[rb];
        int64_t other = (&small == &mutexes[ra]) ? rb : ra;
        return small.count(other) > 0;
    };

    for (int64_t idx : order) {
        int64_t ra = uf.find(uv[2 * idx]);
        int64_t rb = uf.find(uv[2 * idx + 1]);
        if (ra == rb) continue;
        if (attractive[idx]) {
            if (have_mutex(ra, rb)) continue;
            int64_t root = uf.merge(ra, rb);
            int64_t child = (root == ra) ? rb : ra;
            // Merge the child's mutex set into the root and rewrite the
            // partners' back-references child→root.  Invariant: a root's set
            // contains only current roots, and every partner set points back
            // at the current root — so `have_mutex` stays exact.  Snapshot
            // the child's set first: erasing/inserting while iterating the
            // same hashtable is UB when a partner entry aliases it.
            std::vector<int64_t> moved(mutexes[child].begin(),
                                       mutexes[child].end());
            mutexes[child].clear();
            for (int64_t m : moved) {
                mutexes[m].erase(child);
                if (m == root) continue;  // defensive: never self-mutex
                mutexes[m].insert(root);
                mutexes[root].insert(m);
            }
        } else {
            mutexes[ra].insert(rb);
            mutexes[rb].insert(ra);
        }
    }
    for (int64_t i = 0; i < n_nodes; ++i) labels[i] = uf.find(i);
}

}  // extern "C"
