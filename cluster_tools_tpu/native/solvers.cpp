// Native combinatorial graph solvers.
//
// The TPU framework keeps inherently sequential, pointer-chasing graph
// algorithms on the host in C++ (the role nifty/affogato play for the
// reference — SURVEY.md §2.10): greedy additive edge contraction (GAEC)
// multicut, threshold agglomerative clustering, and the mutex watershed.
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
//
// Reference behaviors mirrored:
//   * GAEC: elf.segmentation.multicut 'greedy-additive' solver
//     (multicut/solve_subproblems.py:184, solve_global.py:147-153)
//   * agglomerative clustering: elf mala_clustering / agglomerative_clustering
//     (watershed/agglomerate.py:190-198, agglomerative_clustering.py:138)
//   * mutex watershed: affogato compute_mws_segmentation
//     (mutex_watershed/mws_blocks.py:11)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <queue>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct UnionFind {
    std::vector<int64_t> parent;
    std::vector<int64_t> rank_;

    explicit UnionFind(int64_t n) : parent(n), rank_(n, 0) {
        for (int64_t i = 0; i < n; ++i) parent[i] = i;
    }

    int64_t find(int64_t x) {
        while (parent[x] != x) {
            parent[x] = parent[parent[x]];
            x = parent[x];
        }
        return x;
    }

    // returns the new root (or -1 if already merged)
    int64_t merge(int64_t a, int64_t b) {
        a = find(a);
        b = find(b);
        if (a == b) return -1;
        if (rank_[a] < rank_[b]) std::swap(a, b);
        parent[b] = a;
        if (rank_[a] == rank_[b]) ++rank_[a];
        return a;
    }
};

struct HeapEntry {
    double priority;
    int64_t u, v;
    uint64_t stamp;  // lazy invalidation: entry valid iff stamp matches edge stamp

    bool operator<(const HeapEntry& o) const { return priority < o.priority; }
};

struct EdgeVal {
    double w;  // accumulated value: sum (additive) or weighted mean (mean mode)
    double c;  // accumulated multiplicity (edge count / size)
};

// Dynamic contracted graph: per-root adjacency map root -> (neighbor -> EdgeVal).
struct DynamicGraph {
    std::vector<std::unordered_map<int64_t, EdgeVal>> adj;
    std::unordered_map<uint64_t, uint64_t> edge_stamp;  // key(u,v) -> stamp
    uint64_t stamp_counter = 0;

    explicit DynamicGraph(int64_t n) : adj(n) {}

    static uint64_t key(int64_t u, int64_t v, int64_t n) {
        if (u > v) std::swap(u, v);
        return static_cast<uint64_t>(u) * static_cast<uint64_t>(n) +
               static_cast<uint64_t>(v);
    }
};

// Core greedy agglomeration: repeatedly contract the max-priority edge while
// priority > stop_priority.  Parallel edges accumulate additively
// (mean_mode=false, GAEC) or by count-weighted mean (mean_mode=true,
// mala-style clustering; priority = -mean so the *lowest* boundary merges
// first).  Returns node -> root labels in `labels`.
void greedy_agglomeration(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                          const double* weights, const double* counts,
                          bool mean_mode, double stop_priority,
                          int64_t* labels) {
    UnionFind uf(n_nodes);
    DynamicGraph g(n_nodes);
    std::priority_queue<HeapEntry> heap;

    auto combine = [mean_mode](const EdgeVal& a, const EdgeVal& b) {
        if (mean_mode)
            return EdgeVal{(a.w * a.c + b.w * b.c) / (a.c + b.c), a.c + b.c};
        return EdgeVal{a.w + b.w, a.c + b.c};
    };
    auto priority = [mean_mode](const EdgeVal& e) {
        return mean_mode ? -e.w : e.w;
    };

    for (int64_t e = 0; e < n_edges; ++e) {
        int64_t u = uv[2 * e], v = uv[2 * e + 1];
        if (u == v) continue;
        EdgeVal val{weights[e], counts ? counts[e] : 1.0};
        auto it = g.adj[u].find(v);
        if (it == g.adj[u].end()) {
            g.adj[u][v] = val;
            g.adj[v][u] = val;
        } else {
            EdgeVal merged = combine(it->second, val);
            it->second = merged;
            g.adj[v][u] = merged;
        }
    }
    for (int64_t u = 0; u < n_nodes; ++u) {
        for (const auto& kv : g.adj[u]) {
            if (kv.first > u) {
                uint64_t k = DynamicGraph::key(u, kv.first, n_nodes);
                g.edge_stamp[k] = 0;
                heap.push({priority(kv.second), u, kv.first, 0});
            }
        }
    }

    while (!heap.empty()) {
        HeapEntry top = heap.top();
        heap.pop();
        int64_t u = uf.find(top.u), v = uf.find(top.v);
        if (u == v) continue;
        uint64_t k = DynamicGraph::key(u, v, n_nodes);
        auto st = g.edge_stamp.find(k);
        if (st == g.edge_stamp.end() || st->second != top.stamp) continue;
        if (top.priority <= stop_priority) break;

        // contract v into u (keep the larger adjacency as the base)
        if (g.adj[u].size() < g.adj[v].size()) std::swap(u, v);
        int64_t root = uf.merge(u, v);
        if (root != u) {  // union-by-rank picked v's tree; relabel so data at u
            std::swap(u, v);
        }
        // move v's edges into u
        g.adj[u].erase(v);
        g.adj[v].erase(u);
        for (const auto& kv : g.adj[v]) {
            int64_t w = kv.first;
            g.adj[w].erase(v);
            auto it = g.adj[u].find(w);
            EdgeVal merged;
            if (it == g.adj[u].end()) {
                merged = kv.second;
                g.adj[u][w] = merged;
                g.adj[w][u] = merged;
            } else {
                merged = combine(it->second, kv.second);
                it->second = merged;
                g.adj[w][u] = merged;
            }
            uint64_t nk = DynamicGraph::key(u, w, n_nodes);
            uint64_t stamp = ++g.stamp_counter;
            g.edge_stamp[nk] = stamp;
            heap.push({priority(merged), u, w, stamp});
        }
        g.adj[v].clear();
    }

    for (int64_t i = 0; i < n_nodes; ++i) labels[i] = uf.find(i);
}

// Lifted GAEC: contraction only along local edges, priority = combined
// local+lifted inter-cluster cost, both cost maps merge on contraction
// (nifty's liftedGraphEdgeWeightedClusterPolicy behavior, used by the
// reference through elf's lifted 'greedy-additive' solver).
void lifted_gaec_impl(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                      const double* costs, int64_t n_lifted,
                      const int64_t* lifted_uv, const double* lifted_costs,
                      int64_t* labels) {
    UnionFind uf(n_nodes);
    std::vector<std::unordered_map<int64_t, double>> local(n_nodes);
    std::vector<std::unordered_map<int64_t, double>> lifted(n_nodes);
    std::unordered_map<uint64_t, uint64_t> edge_stamp;
    uint64_t stamp_counter = 0;
    std::priority_queue<HeapEntry> heap;

    for (int64_t e = 0; e < n_edges; ++e) {
        int64_t u = uv[2 * e], v = uv[2 * e + 1];
        if (u == v) continue;
        local[u][v] += costs[e];
        local[v][u] = local[u][v];
    }
    for (int64_t e = 0; e < n_lifted; ++e) {
        int64_t u = lifted_uv[2 * e], v = lifted_uv[2 * e + 1];
        if (u == v) continue;
        lifted[u][v] += lifted_costs[e];
        lifted[v][u] = lifted[u][v];
    }
    auto combined = [&](int64_t u, int64_t v) {
        double c = local[u].at(v);
        auto it = lifted[u].find(v);
        if (it != lifted[u].end()) c += it->second;
        return c;
    };
    for (int64_t u = 0; u < n_nodes; ++u) {
        for (const auto& kv : local[u]) {
            if (kv.first > u) {
                edge_stamp[DynamicGraph::key(u, kv.first, n_nodes)] = 0;
                heap.push({combined(u, kv.first), u, kv.first, 0});
            }
        }
    }

    while (!heap.empty()) {
        HeapEntry top = heap.top();
        heap.pop();
        int64_t u = uf.find(top.u), v = uf.find(top.v);
        if (u == v) continue;
        uint64_t k = DynamicGraph::key(u, v, n_nodes);
        auto st = edge_stamp.find(k);
        if (st == edge_stamp.end() || st->second != top.stamp) continue;
        if (top.priority <= 0.0) break;

        if (local[u].size() + lifted[u].size() <
            local[v].size() + lifted[v].size())
            std::swap(u, v);
        int64_t root = uf.merge(u, v);
        if (root != u) std::swap(u, v);
        local[u].erase(v);
        local[v].erase(u);
        lifted[u].erase(v);
        lifted[v].erase(u);
        std::unordered_set<int64_t> touched;
        for (auto* m : {&local, &lifted}) {
            for (const auto& kv : (*m)[v]) {
                int64_t w = kv.first;
                (*m)[w].erase(v);
                (*m)[u][w] += kv.second;
                (*m)[w][u] = (*m)[u][w];
                touched.insert(w);
            }
            (*m)[v].clear();
        }
        for (const auto& kv : local[u]) touched.insert(kv.first);
        for (int64_t w : touched) {
            if (local[u].find(w) == local[u].end()) continue;  // lifted-only
            uint64_t nk = DynamicGraph::key(u, w, n_nodes);
            uint64_t stamp = ++stamp_counter;
            edge_stamp[nk] = stamp;
            heap.push({combined(u, w), u, w, stamp});
        }
    }

    for (int64_t i = 0; i < n_nodes; ++i) labels[i] = uf.find(i);
}

}  // namespace

extern "C" {

// Lifted multicut via lifted-GAEC (see lifted_gaec_impl).
void lifted_gaec(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                 const double* costs, int64_t n_lifted,
                 const int64_t* lifted_uv, const double* lifted_costs,
                 int64_t* labels) {
    lifted_gaec_impl(n_nodes, n_edges, uv, costs, n_lifted, lifted_uv,
                     lifted_costs, labels);
}

// GAEC multicut: contract while the best merge has positive cost.
// labels receives the root id per node (not consecutive).
void gaec_multicut(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                   const double* costs, int64_t* labels) {
    greedy_agglomeration(n_nodes, n_edges, uv, costs, nullptr,
                         /*mean_mode=*/false, 0.0, labels);
}

// Threshold agglomeration on edge weights where LOW weight = merge first and
// parallel edges combine by size-weighted mean (mala semantics: weights are
// boundary probabilities).  Merges until the cheapest remaining mean boundary
// exceeds `threshold`.  `sizes` may be null (unit sizes).
void agglomerative_clustering(int64_t n_nodes, int64_t n_edges,
                              const int64_t* uv, const double* weights,
                              const double* sizes, double threshold,
                              int64_t* labels) {
    greedy_agglomeration(n_nodes, n_edges, uv, weights, sizes,
                         /*mean_mode=*/true, -threshold, labels);
}

// Mutex watershed on a weighted graph: edges sorted by |weight| descending are
// processed Kruskal-style; attractive edges (attractive[e] != 0) merge unless a
// mutex exists, repulsive edges install mutexes between clusters.
// (affogato's graph MWS algorithm.)
void mutex_watershed(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                     const double* weights, const uint8_t* attractive,
                     int64_t* labels) {
    std::vector<int64_t> order(n_edges);
    for (int64_t i = 0; i < n_edges; ++i) order[i] = i;
    std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
        return weights[a] > weights[b];
    });

    UnionFind uf(n_nodes);
    // per-root mutex partner sets
    std::vector<std::unordered_set<int64_t>> mutexes(n_nodes);

    auto have_mutex = [&](int64_t ra, int64_t rb) {
        const auto& small = mutexes[ra].size() < mutexes[rb].size() ? mutexes[ra]
                                                                    : mutexes[rb];
        int64_t other = (&small == &mutexes[ra]) ? rb : ra;
        return small.count(other) > 0;
    };

    for (int64_t idx : order) {
        int64_t ra = uf.find(uv[2 * idx]);
        int64_t rb = uf.find(uv[2 * idx + 1]);
        if (ra == rb) continue;
        if (attractive[idx]) {
            if (have_mutex(ra, rb)) continue;
            int64_t root = uf.merge(ra, rb);
            int64_t child = (root == ra) ? rb : ra;
            // merge mutex sets into the root; update partners' entries
            if (mutexes[child].size() > mutexes[root].size())
                std::swap(mutexes[child], mutexes[root]);
            for (int64_t m : mutexes[child]) {
                mutexes[root].insert(m);
                mutexes[m].erase(child);
                mutexes[m].insert(root);
            }
            mutexes[child].clear();
        } else {
            mutexes[ra].insert(rb);
            mutexes[rb].insert(ra);
        }
    }
    for (int64_t i = 0; i < n_nodes; ++i) labels[i] = uf.find(i);
}

}  // extern "C"
